"""Rapids — the dataframe expression language behind POST /3/Rapids.

Reference (water/rapids/**, SURVEY §3.6): clients build a lazy client-side
AST (h2o-py expr.py) and flush Lisp-style strings like
``(tmp= tmp_1 (mean (cols frame 'x')))`` to the server; ``Rapids.java:18-40``
parses them, 227 AST prim classes execute over frames with a Session doing
copy-on-write temp tracking.

TPU-native: the interpreter lowers every elementwise prim to jnp ops over the
row-sharded column arrays — one fused XLA program per expression tree (the
reference runs one MRTask per prim; XLA fusion collapses the whole
expression into a single pass).  Reducers ride the sharding's ICI psum.
Strings stay host-side.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o_tpu.core.cloud import cloud
from h2o_tpu.core.diag import DispatchStats
from h2o_tpu.core.frame import (Frame, T_CAT, T_NUM, T_STR, T_TIME, Vec,
                                frame_device_ok)

# ---------------------------------------------------------------------------
# parser (Rapids.java grammar: ( fun args... ), [num list], 'str', ids)
# ---------------------------------------------------------------------------

_TOKEN = re.compile(r"""\s*(,|\(|\)|\[|\]|"[^"]*"|'[^']*'|[^\s(),\[\]]+)""")
_SPAN = re.compile(r"^(-?\d+(?:\.\d+)?):(\d+(?:\.\d+)?)"
                   r"(?::(-?\d+(?:\.\d+)?))?$")


def _expand_numlist(lst) -> List[int]:
    """Flatten numlist entries (plain numbers + spans) to int indices."""
    out: List[int] = []
    for x in lst:
        if isinstance(x, tuple) and x[0] == "span":
            _, lo, cnt, stride = x
            out.extend(int(lo + i * stride) for i in range(int(cnt)))
        else:
            out.append(int(x if isinstance(x, float) else _lit(x)))
    return out


def _tokenize(s: str) -> List[str]:
    out, i = [], 0
    while i < len(s):
        m = _TOKEN.match(s, i)
        if not m:
            break
        out.append(m.group(1))
        i = m.end()
    return out


def _parse(tokens: List[str], pos: int = 0):
    t = tokens[pos]
    if t == "(":
        lst = []
        pos += 1
        while tokens[pos] != ")":
            node, pos = _parse(tokens, pos)
            lst.append(node)
        return lst, pos + 1
    if t == "[":
        lst = []
        pos += 1
        while tokens[pos] != "]":
            if tokens[pos] == ",":
                pos += 1
                continue
            node, pos = _parse(tokens, pos)
            lst.append(node)
        return ("numlist", lst), pos + 1
    if t[0] in "\"'":
        return ("str", t[1:-1]), pos + 1
    m = _SPAN.match(t)
    if m:
        # AstNumList span `lo:cnt[:stride]` (e.g. head() sends [0:5]):
        # expands to lo, lo+stride, ... cnt entries
        lo, cnt, stride = (float(m.group(1)), float(m.group(2)),
                           float(m.group(3) or 1))
        return ("span", lo, cnt, stride), pos + 1
    try:
        return float(t), pos + 1
    except ValueError:
        return ("id", t), pos + 1


def parse(expr: str):
    ast, _ = _parse(_tokenize(expr))
    return ast


# ---------------------------------------------------------------------------
# session & evaluation
# ---------------------------------------------------------------------------

class Session:
    """Temp-frame tracking (water/rapids/Session.java)."""

    def __init__(self, session_id: str = "_default"):
        self.id = session_id
        self.temps: Dict[str, Frame] = {}

    def lookup(self, name: str) -> Any:
        if name in self.temps:
            return self.temps[name]
        v = cloud().dkv.get(name)
        if v is None:
            raise KeyError(f"rapids: unknown id {name!r}")
        return v

    def assign(self, name: str, fr: Frame) -> Frame:
        fr.key = name
        self.temps[name] = fr
        cloud().dkv.put(name, fr)
        return fr

    def remove(self, name: str) -> None:
        self.temps.pop(name, None)
        cloud().dkv.remove(name)


def _as_frame(v) -> Frame:
    if isinstance(v, Frame):
        return v
    if isinstance(v, (int, float)):
        raise TypeError("expected frame, got number")
    raise TypeError(f"expected frame, got {type(v)}")


def _dense(fr: Frame) -> Frame:
    """Canonical-prefix view of a possibly-RAGGED frame (a sharded
    filter/merge output whose valid rows are per-shard prefixes).  The
    four munge verbs consume ragged frames directly by masking; every
    OTHER device consumer (elementwise math, cumops, ifelse, updates)
    assumes the global prefix, so it repacks here first — one balanced
    all_to_all on device, never a host gather."""
    if isinstance(fr, Frame) and fr.is_ragged:
        fr.repack()
    return fr


def _string_compare(op, a, b):
    """==/!= against a string literal over str/categorical columns (the
    reference compares level names, not codes — AstEq string semantics).
    Returns None when not a string comparison."""
    lit = None
    fr = None
    if isinstance(a, Frame) and isinstance(b, str):
        fr, lit = a, b
    elif isinstance(b, Frame) and isinstance(a, str):
        fr, lit = b, a
    elif isinstance(a, Frame) and any(v.type == T_STR for v in a.vecs) \
            and isinstance(b, (int, float)):
        fr, lit = a, str(b)
    if fr is None or not any(v.type in (T_STR, T_CAT) for v in fr.vecs):
        return None
    _dense(fr)
    vecs = []
    for v in fr.vecs:
        if v.type == T_STR:
            eq = np.array([x == lit for x in v.host_data], np.float32)
        elif v.type == T_CAT:
            dom = v.domain or []
            code = dom.index(lit) if lit in dom else -2
            if v.data is not None:
                # code comparison runs on device — no host pull of the
                # code column just to compare against one level code
                eq_dev = (v.data == code).astype(jnp.float32)
                vecs.append(Vec(eq_dev if op == "==" else 1.0 - eq_dev,
                                nrows=v.nrows))
                continue
            eq = (np.asarray(v.to_numpy()) == code).astype(np.float32)
        else:
            eq = np.zeros(v.nrows, np.float32)
        vecs.append(Vec(eq if op == "==" else 1.0 - eq))
    return Frame(list(fr.names), vecs)


def _has_time(x) -> bool:
    return isinstance(x, Frame) and any(v.type == T_TIME for v in x.vecs)


def _elementwise(op, a, b=None, name=None):
    """Apply a jnp op over frames/scalars, broadcasting column-wise.

    Binary ops that involve a T_TIME column run on the exact float64
    host copies instead of the f32 device payload (epoch-ms rounding,
    see _NP_BINOPS note)."""
    if isinstance(a, Frame):
        _dense(a)
    if isinstance(b, Frame):
        _dense(b)
    if b is not None and name in _NP_BINOPS and \
            (_has_time(a) or _has_time(b)):
        npop = _NP_BINOPS[name]

        def col(x, i):
            if not isinstance(x, Frame):
                return x
            v = x.vecs[i if x.ncols > 1 else 0]
            return np.asarray(v.to_numpy(), np.float64)

        def is_time(x, i):
            return isinstance(x, Frame) and \
                x.vecs[i if x.ncols > 1 else 0].type == T_TIME

        af, bf = isinstance(a, Frame), isinstance(b, Frame)
        base = a if (af and (not bf or a.ncols >= b.ncols)) else b
        n = base.ncols
        nrows = base.nrows
        vecs = []
        for i in range(n):
            res = npop(col(a, i), col(b, i))
            # time ± number stays a time (epoch-scale values must keep
            # the exact f64 host copy); time - time is a duration
            keep_time = name in ("+", "-") and \
                (is_time(a, i) != is_time(b, i))
            vecs.append(Vec(res, T_TIME, nrows=nrows) if keep_time
                        else Vec(res, nrows=nrows))
        return Frame(list(base.names), vecs)
    if b is None:
        fr = _as_frame(a)
        vecs = [Vec(op(v.as_float()), nrows=fr.nrows) for v in fr.vecs]
        return Frame(list(fr.names), vecs)
    af, bf = isinstance(a, Frame), isinstance(b, Frame)
    if af and bf:
        assert a.nrows == b.nrows, "frame row mismatch"
        n = max(a.ncols, b.ncols)
        vecs = []
        for i in range(n):
            va = a.vecs[i if a.ncols > 1 else 0].as_float()
            vb = b.vecs[i if b.ncols > 1 else 0].as_float()
            vecs.append(Vec(op(va, vb), nrows=a.nrows))
        names = (a if a.ncols >= b.ncols else b).names
        return Frame(list(names), vecs)
    if af:
        return Frame(list(a.names),
                     [Vec(op(v.as_float(), b), nrows=a.nrows)
                      for v in a.vecs])
    if bf:
        return Frame(list(b.names),
                     [Vec(op(a, v.as_float()), nrows=b.nrows)
                      for v in b.vecs])
    return op(a, b)


def _reduce_all(op_masked, fr: Frame):
    """Reduce over all numeric cells of a frame -> python float."""
    fr = _as_frame(fr)
    vals = []
    for v in fr.vecs:
        if not (v.is_numeric or v.is_categorical):
            continue
        vals.append(op_masked(v))
    if len(vals) == 1:
        return vals[0]
    return vals


def _col_indices(fr: Frame, sel) -> List[int]:
    if isinstance(sel, tuple) and sel[0] == "numlist":
        try:
            return _expand_numlist(sel[1])
        except (TypeError, ValueError):
            # string names in the list
            return [fr.names.index(_lit(x)) for x in sel[1]]
    if isinstance(sel, tuple) and sel[0] == "str":
        return [fr.names.index(sel[1])]
    if isinstance(sel, float):
        return [int(sel)]
    raise TypeError(f"bad column selector {sel}")


def _lit(node):
    if isinstance(node, tuple) and node[0] in ("str", "id"):
        return node[1]
    return node


def _host_oracle(fn, *args):
    """Run a ``*_host`` parity oracle under the munge phase scope (host
    pulls stay attributed) — the OOM ladder's rung-(c) entry point."""
    with DispatchStats.phase_scope("munge"):
        return fn(*args)


def _row_select(fr: Frame, sel, sess) -> Frame:
    if isinstance(sel, Frame):  # boolean mask frame
        mv = sel.vecs[0]
        from h2o_tpu.core.munge import device_munge_enabled
        from h2o_tpu.core.oom import oom_ladder
        if device_munge_enabled() and frame_device_ok(fr) and \
                mv.data is not None:
            # device compaction: the mask never lands on host; only the
            # surviving row count syncs (core/munge.filter_rows).  On
            # device OOM the ladder sweeps the HBM LRU and finally runs
            # the host parity oracle (same rows by the parity contract).
            return oom_ladder(
                "munge.filter", lambda: fr.slice_rows(mv.data),
                host_fallback=lambda: _host_oracle(
                    _row_select_mask_host, fr, mv))
        with DispatchStats.phase_scope("munge"):
            return _row_select_mask_host(fr, mv)
    elif isinstance(sel, tuple) and sel[0] == "numlist":
        idx = np.asarray(_expand_numlist(sel[1]), np.int64)
    elif isinstance(sel, tuple) and sel[0] == "span":
        idx = np.asarray(_expand_numlist([sel]), np.int64)
    else:
        idx = np.asarray([int(sel)], np.int64)
    from h2o_tpu.core.munge import device_munge_enabled
    from h2o_tpu.core.oom import oom_ladder
    if device_munge_enabled() and frame_device_ok(fr) and \
            np.all(idx >= 0):
        # explicit index lists run as a device gather (munge.take_rows):
        # the index uploads once, no column round-trips host
        return oom_ladder(
            "munge.take", lambda: fr.slice_rows(idx),
            host_fallback=lambda: _host_oracle(_row_select_host, fr,
                                               idx))
    return _row_select_host(fr, idx)


def _row_select_mask_host(fr: Frame, mask_vec: Vec) -> Frame:
    """Host fallback for boolean-mask selection: pull the mask, gather."""
    mask = np.asarray(mask_vec.to_numpy(), np.float64) > 0
    return _row_select_host(fr, np.nonzero(mask)[0])


def _row_select_host(fr: Frame, idx: np.ndarray) -> Frame:
    """Host gather + re-upload fallback (explicit index lists, and the
    parity oracle for the device boolean-mask compaction)."""
    vecs = []
    for v in fr.vecs:
        data = v.to_numpy()[idx]
        vecs.append(Vec(data, v.type, domain=v.domain)
                    if v.type != T_CAT else
                    Vec(data.astype(np.int32), T_CAT, domain=v.domain))
    return Frame(list(fr.names), vecs)


def _masked(fn_np):
    """Build a host reducer over one Vec using rollups when possible."""
    return fn_np


class _Env:
    def __init__(self, session: Session):
        self.s = session
        self.locals: Dict[str, Any] = {}   # apply-lambda arg bindings


_BINOPS = {
    "+": jnp.add, "-": jnp.subtract, "*": jnp.multiply, "/": jnp.divide,
    "^": jnp.power, "%": jnp.mod, "%%": jnp.mod,
    "intDiv": lambda a, b: jnp.floor_divide(a, b),
    "<": lambda a, b: (a < b).astype(jnp.float32),
    "<=": lambda a, b: (a <= b).astype(jnp.float32),
    ">": lambda a, b: (a > b).astype(jnp.float32),
    ">=": lambda a, b: (a >= b).astype(jnp.float32),
    "==": lambda a, b: (a == b).astype(jnp.float32),
    "!=": lambda a, b: (a != b).astype(jnp.float32),
    "&": lambda a, b: ((a != 0) & (b != 0)).astype(jnp.float32),
    "|": lambda a, b: ((a != 0) | (b != 0)).astype(jnp.float32),
}

# numpy twins for the exact-f64 host path: T_TIME epoch-ms exceeds f32
# precision (~4 min ulp at 2026 epochs), so arithmetic/comparisons that
# touch a time column run on the exact host copy (Vec.to_numpy)
_NP_BINOPS = {
    "+": np.add, "-": np.subtract, "*": np.multiply, "/": np.divide,
    "^": np.power, "%": np.mod, "%%": np.mod,
    "intDiv": np.floor_divide,
    "<": lambda a, b: (a < b).astype(np.float64),
    "<=": lambda a, b: (a <= b).astype(np.float64),
    ">": lambda a, b: (a > b).astype(np.float64),
    ">=": lambda a, b: (a >= b).astype(np.float64),
    "==": lambda a, b: (a == b).astype(np.float64),
    "!=": lambda a, b: (a != b).astype(np.float64),
    "&": lambda a, b: ((a != 0) & (b != 0)).astype(np.float64),
    "|": lambda a, b: ((a != 0) | (b != 0)).astype(np.float64),
}

_UNOPS = {
    "abs": jnp.abs, "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10,
    "log2": jnp.log2, "log1p": jnp.log1p, "sqrt": jnp.sqrt,
    "floor": jnp.floor, "ceiling": jnp.ceil, "round": jnp.round,
    "trunc": jnp.trunc, "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "asin": jnp.arcsin, "acos": jnp.arccos, "atan": jnp.arctan,
    "sign": jnp.sign, "signif": jnp.round,
    "!": lambda a: (a == 0).astype(jnp.float32),
    "is.na": lambda a: jnp.isnan(a).astype(jnp.float32),
}


def _offer_plan(op, node, env):
    """Offer a fusable terminal verb to the lazy planner
    (rapids/plan.py) before the eager per-verb handler runs.  Returns
    the fused region's Frame or None (None = the eager path — which is
    also the planner's bitwise parity oracle — proceeds untouched)."""
    from h2o_tpu.rapids.plan import try_plan
    return try_plan(op, node, env, _eval)


def _eval(node, env: _Env):
    s = env.s
    if isinstance(node, float):
        return node
    if isinstance(node, tuple):
        tag = node[0]
        if tag == "str":
            return node
        if tag == "id":
            name = node[1]
            # Rapids boolean/NA literals (Rapids.java grammar; the client
            # serializes python bools as bare True/False ids)
            if name in ("TRUE", "True", "true"):
                return 1.0
            if name in ("FALSE", "False", "false"):
                return 0.0
            if name in ("NA", "NaN", "nan"):
                return float("nan")
            if name in env.locals:
                return env.locals[name]
            return s.lookup(name)
        if tag == "numlist":
            return node
    if not isinstance(node, list):
        raise TypeError(f"bad node {node}")
    head = node[0]
    op = head[1] if isinstance(head, tuple) else head

    if op == "tmp=":
        name = _lit(node[1])
        val = _eval(node[2], env)
        return s.assign(name, _as_frame(val))
    if op in ("rm", "rm_fr"):
        s.remove(_lit(node[1]))
        return None
    if op in ("cols", "cols_py"):
        fr = _as_frame(_eval(node[1], env))
        sel = node[2] if isinstance(node[2], tuple) else _eval(node[2], env)
        idxs = _col_indices(fr, sel)
        return fr.subframe([fr.names[i] for i in idxs])
    if op in ("rows", "rows_py"):
        fused = _offer_plan(op, node, env)
        if fused is not None:
            return fused
        fr = _as_frame(_eval(node[1], env))
        sel = node[2]
        if isinstance(sel, list):
            sel = _eval(sel, env)
        return _row_select(fr, sel, s)
    if op == "nrow":
        return float(_as_frame(_eval(node[1], env)).nrows)
    if op == "ncol":
        return float(_as_frame(_eval(node[1], env)).ncols)
    if op == "colnames":
        return [("str", n) for n in _as_frame(_eval(node[1], env)).names]
    if op == "colnames=":
        fr = _as_frame(_eval(node[1], env))
        names = [_lit(x) for x in node[3][1]] if isinstance(node[3], tuple) \
            else [_lit(node[3])]
        fr.names = list(names)
        return fr
    if op == "cbind":
        frames = [_dense(_as_frame(_eval(a, env))) for a in node[1:]]
        out = frames[0]
        for f2 in frames[1:]:
            out = out.cbind(f2)
        return out
    if op == "rbind":
        frames = [_as_frame(_eval(a, env)) for a in node[1:]]
        names = frames[0].names
        vecs = []
        for j, n in enumerate(names):
            parts = [f.vecs[j].to_numpy() for f in frames]
            v0 = frames[0].vecs[j]
            data = np.concatenate(parts)
            vecs.append(Vec(data if v0.type != T_CAT else
                            data.astype(np.int32), v0.type,
                            domain=v0.domain))
        return Frame(list(names), vecs)
    if op in _BINOPS:
        a = _eval(node[1], env)
        b = _eval(node[2], env)
        if op in ("==", "!="):
            sc = _string_compare(op,
                                 a[1] if isinstance(a, tuple) and
                                 a[0] == "str" else a,
                                 b[1] if isinstance(b, tuple) and
                                 b[0] == "str" else b)
            if sc is not None:
                return sc
        return _elementwise(_BINOPS[op], a, b, name=op)
    if op in _UNOPS:
        return _elementwise(_UNOPS[op], _eval(node[1], env))
    if op in ("sumNA", "minNA", "maxNA", "meanNA", "medianNA", "sdNA",
              "varNA"):
        op = op[:-2]    # NA-skipping variants; rollups already skip NAs
    if op in ("mean", "sum", "min", "max", "sd", "var", "median"):
        fr = _as_frame(_eval(node[1], env))
        axis_form = len(node) > 2      # (op fr na_rm axis): AstMean.java
        na_rm = bool(_eval(node[2], env)) if axis_form else True
        axis = int(_eval(node[3], env)) if len(node) > 3 else 0
        def red(v):
            r = v.rollups
            if op == "mean":
                return float(r.mean)
            if op == "sum":
                return float(r.mean * r.cnt)
            if op == "min":
                return float(r.min)
            if op == "max":
                return float(r.max)
            if op == "sd":
                return float(r.sigma)
            if op == "var":
                return float(r.sigma ** 2)
            from h2o_tpu.core.quantile import quantile_vec
            return float(quantile_vec(v, 0.5))
        if axis_form and axis == 1:
            # row-wise (AstMean.java:57 rowwiseMean): (nrows, 1) frame
            mats = [np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
                    for v in fr.vecs if not v.is_categorical]
            M = np.stack(mats, axis=1) if mats else \
                np.zeros((fr.nrows, 0))
            if op == "mean":
                vals = np.nanmean(M, axis=1) if na_rm else M.mean(axis=1)
            elif op == "sum":
                vals = np.nansum(M, axis=1) if na_rm else M.sum(axis=1)
            elif op == "min":
                vals = np.nanmin(M, axis=1) if na_rm else M.min(axis=1)
            elif op == "max":
                vals = np.nanmax(M, axis=1) if na_rm else M.max(axis=1)
            elif op == "median":
                vals = np.nanmedian(M, axis=1) if na_rm else \
                    np.median(M, axis=1)
            else:
                ddof_fn = np.nanstd if na_rm else np.std
                vals = ddof_fn(M, axis=1, ddof=1)
                if op == "var":
                    vals = vals ** 2
            return Frame([op], [Vec(vals.astype(np.float32))])
        if axis_form:
            # (op fr na_rm 0): ONE-ROW frame of per-column reductions
            # (the client's frame.mean() -> getrow flow).  na_rm=False
            # returns NA for columns containing NAs (AstMean.java:68)
            def col_val(v):
                if v.is_categorical:
                    return np.nan
                if not na_rm and float(v.rollups.nacnt) > 0:
                    return np.nan
                return red(v)
            return Frame(
                list(fr.names),
                [Vec(np.array([col_val(v)], np.float32))
                 for v in fr.vecs])
        return _reduce_all(red, fr)
    if op == "quantile":
        fr = _as_frame(_eval(node[1], env))
        probs = [float(x) for x in node[2][1]]
        from h2o_tpu.core.quantile import quantile
        q = quantile(fr, probs)
        cols = {"Probs": np.asarray(probs, np.float32)}
        for c, vals in q.items():
            cols[f"{c}Quantiles"] = np.asarray(vals, np.float32)
        return Frame.from_dict(cols)
    if op == "ifelse":
        cond = _eval(node[1], env)
        a = _eval(node[2], env)
        b = _eval(node[3], env)
        cf = _dense(_as_frame(cond))
        if isinstance(a, Frame):
            _dense(a)
        if isinstance(b, Frame):
            _dense(b)
        cv = cf.vecs[0].as_float()
        av = a.vecs[0].as_float() if isinstance(a, Frame) else a
        bv = b.vecs[0].as_float() if isinstance(b, Frame) else b
        return Frame(["ifelse"],
                     [Vec(jnp.where(cv != 0, av, bv), nrows=cf.nrows)])
    if op in ("asfactor", "as.factor"):
        fr = _dense(_as_frame(_eval(node[1], env)))
        out = []
        for v in fr.vecs:
            if v.is_categorical:
                out.append(v)
            else:
                data = v.to_numpy()
                vals = np.unique(data[~np.isnan(data)])
                lut = {x: i for i, x in enumerate(vals)}
                codes = np.array([lut.get(x, -1) if not math.isnan(x)
                                  else -1 for x in data], np.int32)
                dom = [str(int(x)) if x == int(x) else str(x) for x in vals]
                out.append(Vec(codes, T_CAT, domain=dom))
        return Frame(list(fr.names), out)
    if op in ("asnumeric", "as.numeric"):
        fr = _dense(_as_frame(_eval(node[1], env)))
        out = []
        for v in fr.vecs:
            if v.is_categorical:
                # numeric-looking domains convert by value, else by
                # code; ONE pull of the code column either way
                codes = v.to_numpy()
                try:
                    dom = np.asarray([float(d) for d in v.domain],
                                     np.float32)
                    vals = np.where(codes < 0, np.nan,
                                    dom[np.clip(codes, 0, None)])
                except ValueError:
                    vals = np.where(codes < 0, np.nan,
                                    codes.astype(np.float32))
                out.append(Vec(vals.astype(np.float32), T_NUM))
            else:
                out.append(v)
        return Frame(list(fr.names), out)
    if op == "levels":
        # AstLevels returns a FRAME: one column per input column, rows =
        # level labels NA-padded (client: frame.py levels() zips columns)
        fr = _as_frame(_eval(node[1], env))
        doms = [list(v.domain or []) for v in fr.vecs]
        width = max((len(d) for d in doms), default=0) or 1
        vecs = []
        for d in doms:
            codes = np.asarray(list(range(len(d))) +
                               [-1] * (width - len(d)), np.int32)
            vecs.append(Vec(codes, T_CAT, domain=d or ["_"]))
        return Frame(list(fr.names), vecs)
    if op == "unique":
        fr = _as_frame(_eval(node[1], env))
        v = fr.vecs[0]
        u = np.unique(v.to_numpy())
        u = u[~np.isnan(u)] if u.dtype.kind == "f" else u
        return Frame(["unique"], [Vec(u.astype(np.float32))])
    if op == ":":  # range start:end inclusive -> numlist
        a = int(_eval(node[1], env))
        b = int(_eval(node[2], env))
        return ("numlist", [float(i) for i in range(a, b + 1)])
    if op == "assign":
        name = _lit(node[1])
        return s.assign(name, _as_frame(_eval(node[2], env)))
    if op == "sort":
        fused = _offer_plan(op, node, env)
        return fused if fused is not None else _sort(node, env)
    if op == "merge":
        return _merge(node, env)
    if op in ("GB", "groupby"):
        fused = _offer_plan(op, node, env)
        return fused if fused is not None else _groupby(node, env)
    if op == "table":
        return _table(node, env)
    if op in _CUMOPS:
        fr = _dense(_as_frame(_eval(node[1], env)))
        fn = _CUMOPS[op]
        vecs = []
        for v in fr.vecs:
            x = v.as_float()
            mask = jnp.arange(x.shape[0]) < fr.nrows
            x = jnp.where(mask & ~jnp.isnan(x), x, _CUM_IDENT[op])
            vecs.append(Vec(fn(x), nrows=fr.nrows))
        return Frame(list(fr.names), vecs)
    if op in _STROPS:
        return _string_op(op, node, env)
    if op in ("year", "month", "day", "dayOfWeek", "hour", "minute",
              "second", "week"):
        return _time_part(op, node, env)
    if op == "na.omit":
        fused = _offer_plan(op, node, env)
        if fused is not None:
            return fused
        fr = _as_frame(_eval(node[1], env))
        from h2o_tpu.core.munge import device_munge_enabled
        if device_munge_enabled() and frame_device_ok(fr):
            # NA mask + row compaction entirely on device (cat NA codes
            # appear as NaN through as_matrix's as_float view)
            with DispatchStats.phase_scope("munge"):
                keep = ~jnp.isnan(fr.as_matrix()).any(axis=1)
            return fr.slice_rows(keep)
        keep = np.ones(fr.nrows, bool)
        for v in fr.vecs:
            if v.data is None:
                continue
            d = v.to_numpy()
            keep &= (d >= 0) if v.is_categorical else ~np.isnan(d)
        return fr.slice_rows(keep)
    if op == "naCnt":
        fr = _as_frame(_eval(node[1], env))
        out = []
        for v in fr.vecs:
            if v.host_data is not None:
                out.append(float(sum(x is None for x in v.host_data)))
            else:
                # rollups / device reduction — counting NAs must not
                # pull the whole column to host
                out.append(float(v.nacnt()))
        return out
    if op == "which":
        fr = _as_frame(_eval(node[1], env))
        d = np.asarray(fr.vecs[0].to_numpy())
        hits = np.flatnonzero((d != 0) & ~np.isnan(d))
        return Frame(["which"], [Vec(hits.astype(np.float64))])
    if op in ("is.factor", "anyfactor"):
        fr = _as_frame(_eval(node[1], env))
        flags = [v.is_categorical for v in fr.vecs]
        if op == "anyfactor":
            return float(any(flags))
        return [float(f) for f in flags]   # ValNums: one per column
    if op == "is.numeric":
        fr = _as_frame(_eval(node[1], env))
        return [float(v.is_numeric) for v in fr.vecs]
    if op == ":=":
        return _update(node, env)
    if op == "append":
        fr = _dense(_as_frame(_eval(node[1], env)))
        col = _dense(_as_frame(_eval(node[2], env)))
        name = _lit(node[3])
        out = Frame(list(fr.names), list(fr.vecs))
        out.add(name, col.vecs[0])
        return out
    if op == "h2o.impute":
        return _impute(node, env)
    if op in _EXTRA_OPS:
        return _EXTRA_OPS[op](node, env)
    raise NotImplementedError(f"rapids op {op!r}")


# ---------------------------------------------------------------------------
# sort / merge / groupby / strings (reference: rapids/Merge.java,
# RadixOrder.java, ast/prims/mungers/AstGroup.java, ast/prims/string/*)
# ---------------------------------------------------------------------------

# lax.cummin/cummax rather than jnp.minimum.accumulate — the ufunc
# .accumulate spelling only exists on newer jax
_CUMOPS = {"cumsum": jnp.cumsum, "cumprod": jnp.cumprod,
           "cummin": jax.lax.cummin, "cummax": jax.lax.cummax}
_CUM_IDENT = {"cumsum": 0.0, "cumprod": 1.0, "cummin": jnp.inf,
              "cummax": -jnp.inf}


def _sort_keys(fr: Frame, idxs, ascending) -> np.ndarray:
    keys = []
    for j, asc in zip(reversed(idxs), reversed(ascending)):
        v = fr.vecs[j]
        k = np.asarray(v.to_numpy(), np.float64)
        na = np.isnan(k)
        if v.type == T_CAT:
            na = na | (k < 0)       # categorical NA code is -1
        k = k if asc else -k
        # NAs group first in both directions (RadixOrder's consistent NA
        # placement) — a plain negation would sort cat-NA (-1 -> +1)
        # between levels, and lexsort always puts NaN last.
        keys.append(np.where(na, -np.inf, k))
    return np.lexsort(keys)


def _sort(node, env):
    """(sort fr [cols] [ascending]) — RadixOrder.java analog.  Device
    path (H2O_TPU_DEVICE_MUNGE=1): key ranking is a jnp.lexsort kernel
    and the reorder a device gather — zero host pulls.  Host fallback:
    numpy lexsort over pulled key copies + slice_rows re-upload.
    Columns select by index OR name (the client serializes names:
    frame.sort(by=['y']))."""
    fr = _as_frame(_eval(node[1], env))
    idxs = [fr.names.index(x[1]) if isinstance(x, tuple) and
            x[0] == "str" else int(x) for x in node[2][1]]
    asc = [bool(int(x)) for x in node[3][1]] if len(node) > 3 \
        else [True] * len(idxs)
    from h2o_tpu.core.munge import device_munge_enabled, sort_frame
    from h2o_tpu.core.oom import oom_ladder
    if device_munge_enabled() and frame_device_ok(fr):
        return oom_ladder(
            "munge.sort", lambda: sort_frame(fr, idxs, asc),
            host_fallback=lambda: _host_oracle(_sort_host, fr, idxs,
                                               asc))
    return _host_oracle(_sort_host, fr, idxs, asc)


def _sort_host(fr: Frame, idxs, asc) -> Frame:
    """Host lexsort fallback and parity oracle for the device sort.
    Gathers via the pure-host row-select (NOT Frame.slice_rows, whose
    integer-index path now routes to the device take kernel — an oracle
    must never dispatch the layer it oracles)."""
    order = _sort_keys(fr, idxs, asc)
    return _row_select_host(fr, order)


def _key_codes(fr: Frame, cols: List[int]):
    """Rows -> dense group codes over the named key columns.  Numeric
    NaN keys canonicalize to a -inf sentinel so all NA rows form ONE
    group that sorts first (np.unique treats each NaN row as distinct —
    AstGroup's NA group semantics need them merged); the categorical NA
    code -1 is already a single first-sorting group value.  The device
    factorize kernel (core/munge.py) uses the same sentinel."""
    mats = []
    for j in cols:
        v = fr.vecs[j]
        d = np.asarray(v.to_numpy(), np.float64)
        if not v.is_categorical:
            d = np.where(np.isnan(d), -np.inf, d)
        mats.append(d)
    stacked = np.stack(mats, axis=1)
    uniq, inv = np.unique(stacked, axis=0, return_inverse=True)
    return uniq, inv.ravel()


def _merge(node, env):
    """(merge left right all_x all_y [by_x] [by_y] method) — the radix
    join (rapids/Merge.java, BinaryMerge.java).  Device path: sorted
    join over a shared dense code space (core/munge.merge_frames), only
    the output row count syncing to host.  Host fallback: string-join
    sort-merge over dense key codes."""
    L = _as_frame(_eval(node[1], env))
    R = _as_frame(_eval(node[2], env))
    all_x = bool(int(_eval(node[3], env)))
    all_y = bool(int(_eval(node[4], env)))
    by_x = [int(x) for x in node[5][1]] if len(node) > 5 and node[5][1] \
        else None
    by_y = [int(x) for x in node[6][1]] if len(node) > 6 and node[6][1] \
        else None
    if by_x is None:
        common = [n for n in L.names if n in R.names]
        by_x = [L.names.index(n) for n in common]
        by_y = [R.names.index(n) for n in common]
    from h2o_tpu.core.munge import (device_munge_enabled, merge_device_ok,
                                    merge_frames)
    from h2o_tpu.core.oom import oom_ladder
    if device_munge_enabled() and merge_device_ok(L, R, by_x, by_y):
        return oom_ladder(
            "munge.merge",
            lambda: merge_frames(L, R, all_x, all_y, by_x, by_y),
            host_fallback=lambda: _host_oracle(_merge_host, L, R, all_x,
                                               all_y, by_x, by_y))
    return _host_oracle(_merge_host, L, R, all_x, all_y, by_x, by_y)


def _merge_host(L: Frame, R: Frame, all_x: bool, all_y: bool,
                by_x: List[int], by_y: List[int]) -> Frame:
    """Host sort-merge fallback and parity oracle for the device join."""
    # unify key space: categorical keys match by LABEL, numeric by value
    def key_matrix(fr, cols):
        out = []
        for j in cols:
            v = fr.vecs[j]
            if v.is_categorical:
                out.append(np.asarray(
                    [v.domain[c] if c >= 0 else "\0NA" for c in
                     v.to_numpy()], object))
            else:
                out.append(np.asarray(v.to_numpy(), object))
        return np.stack(out, axis=1)

    lk = key_matrix(L, by_x)
    rk = key_matrix(R, by_y)
    both = np.concatenate([lk, rk])
    # factorize rows of the combined key matrix
    flat = np.asarray(["\1".join(map(str, row)) for row in both])
    uniq, inv = np.unique(flat, return_inverse=True)
    lcode, rcode = inv[: len(lk)], inv[len(lk):]
    # build right-side lookup: code -> row indices
    r_order = np.argsort(rcode, kind="stable")
    r_sorted = rcode[r_order]
    starts = np.searchsorted(r_sorted, np.arange(len(uniq)), side="left")
    ends = np.searchsorted(r_sorted, np.arange(len(uniq)), side="right")
    li, ri = [], []
    matched_r = np.zeros(len(rk), bool)
    for i, c in enumerate(lcode):
        lo, hi = starts[c], ends[c]
        if hi > lo:
            for r in r_order[lo:hi]:
                li.append(i)
                ri.append(r)
                matched_r[r] = True
        elif all_x:                      # left outer: keep unmatched left
            li.append(i)
            ri.append(-1)
    if all_y:
        for r in np.flatnonzero(~matched_r):
            li.append(-1)
            ri.append(int(r))
    li = np.asarray(li, np.int64)
    ri = np.asarray(ri, np.int64)

    names, vecs = [], []
    r_by = set(by_y)
    for j, n in enumerate(L.names):
        v = L.vecs[j]
        d = v.to_numpy()
        take = np.where(li >= 0, li, 0)
        out = d[take]
        if v.is_categorical:
            out = np.where(li >= 0, out, -1).astype(np.int32)
            dom = list(v.domain)
            # right-only rows: pull key values from the right frame; the
            # output domain is the UNION so unseen right labels survive
            if j in by_x and (li < 0).any():
                jr = by_y[by_x.index(j)]
                rv = R.vecs[jr]
                rd = rv.to_numpy()
                dom = dom + [x for x in rv.domain if x not in set(dom)]
                remap = _domain_remap(rv.domain, dom)
                out = np.where(li >= 0, out,
                               remap[np.clip(rd[np.where(ri >= 0, ri, 0)],
                                             -1, None)]).astype(np.int32)
            vecs.append(Vec(out, T_CAT, domain=dom))
        else:
            out = np.where(li >= 0, out, np.nan)
            if j in by_x and (li < 0).any():
                jr = by_y[by_x.index(j)]
                rd = np.asarray(R.vecs[jr].to_numpy(), np.float64)
                out = np.where(li >= 0, out,
                               rd[np.where(ri >= 0, ri, 0)])
            vecs.append(Vec(out.astype(np.float32), v.type))
        names.append(n)
    for j, n in enumerate(R.names):
        if j in r_by:
            continue
        v = R.vecs[j]
        d = v.to_numpy()
        take = np.where(ri >= 0, ri, 0)
        out = d[take]
        if v.is_categorical:
            out = np.where(ri >= 0, out, -1).astype(np.int32)
            vecs.append(Vec(out, T_CAT, domain=list(v.domain)))
        else:
            out = np.where(ri >= 0, out, np.nan)
            vecs.append(Vec(out.astype(np.float32), v.type))
        names.append(n if n not in names else f"{n}_y")
    return Frame(names, vecs)


def _domain_remap(src_dom, dst_dom):
    """Map src categorical codes into dst's domain (-1 for unseen);
    index -1 (NA) maps to -1 via the last slot."""
    lut = {d: i for i, d in enumerate(dst_dom)}
    remap = np.full(len(src_dom) + 1, -1, np.int32)
    for i, d in enumerate(src_dom):
        remap[i] = lut.get(d, -1)
    return remap


_GB_AGGS = ("min", "max", "mean", "sum", "sd", "var", "nrow", "count",
            "median", "mode")


def _groupby(node, env):
    """(GB fr [group_idxs] agg col na_method ...) — AstGroup.java.
    Device path (core/munge.groupby_frame): shard-resident partials +
    cross-shard combine for the combinable bundle (or the global fused
    segment pass — device median via the segment order-statistic
    kernel, categorical ``mode`` via the segment-bincount + argmax
    kernel); only the group count syncs.  Numeric / high-cardinality
    ``mode`` and non-device frames fall back to the host path."""
    fr = _as_frame(_eval(node[1], env))
    gcols = [int(x) for x in node[2][1]]
    aggs = []
    i = 3
    while i < len(node):
        a = _lit(node[i])
        if a not in _GB_AGGS:
            break
        if i + 1 >= len(node):
            raise ValueError(f"groupby agg {a!r} is missing its column")
        col = node[i + 1]
        col_i = int(col) if isinstance(col, float) else \
            fr.names.index(_lit(col))
        na = _lit(node[i + 2]) if i + 2 < len(node) else "all"
        aggs.append((a, col_i, na))
        i += 3
    from h2o_tpu.core.munge import (DEVICE_AGGS, device_munge_enabled,
                                    groupby_frame, mode_device_eligible)
    from h2o_tpu.core.oom import oom_ladder
    if device_munge_enabled() and frame_device_ok(fr) and \
            all(a in DEVICE_AGGS for a, _c, _n in aggs) and \
            mode_device_eligible(fr, aggs):
        return oom_ladder(
            "munge.groupby", lambda: groupby_frame(fr, gcols, aggs),
            host_fallback=lambda: _host_oracle(_groupby_host, fr, gcols,
                                               aggs))
    return _host_oracle(_groupby_host, fr, gcols, aggs)


def _groupby_host(fr: Frame, gcols: List[int], aggs) -> Frame:
    """Host bincount/searchsorted fallback and parity oracle."""
    uniq, inv = _key_codes(fr, gcols)
    G = len(uniq)
    names, vecs = [], []
    for k, j in enumerate(gcols):
        v = fr.vecs[j]
        col = uniq[:, k]
        if v.is_categorical:
            vecs.append(Vec(col.astype(np.int32), T_CAT,
                            domain=list(v.domain)))
        else:
            # the -inf NA-group sentinel reads back as NaN
            col = np.where(np.isneginf(col), np.nan, col)
            vecs.append(Vec(col.astype(np.float32), v.type))
        names.append(fr.names[j])
    counts = np.bincount(inv, minlength=G)
    for a, col_i, na in aggs:
        v_agg = fr.vecs[col_i]
        d = np.asarray(v_agg.to_numpy(), np.float64)
        ok = (d >= 0) if v_agg.is_categorical else ~np.isnan(d)
        di = np.where(ok, d, 0.0)
        cnt_ok = np.bincount(inv, weights=ok.astype(np.float64),
                             minlength=G)
        if a in ("nrow", "count"):
            out = counts.astype(np.float64)
        elif a == "sum":
            out = np.bincount(inv, weights=di, minlength=G)
        elif a == "mean":
            out = np.bincount(inv, weights=di, minlength=G) / \
                np.maximum(cnt_ok, 1)
        elif a in ("sd", "var"):
            m = np.bincount(inv, weights=di, minlength=G) / \
                np.maximum(cnt_ok, 1)
            ss = np.bincount(inv, weights=di * di, minlength=G)
            var = ss / np.maximum(cnt_ok, 1) - m * m
            var = var * cnt_ok / np.maximum(cnt_ok - 1, 1)
            out = np.sqrt(np.maximum(var, 0)) if a == "sd" else \
                np.maximum(var, 0)
        elif a in ("min", "max"):
            out = np.full(G, np.inf if a == "min" else -np.inf)
            ufunc = np.minimum if a == "min" else np.maximum
            ufunc.at(out, inv[ok], d[ok])
            out[~np.isfinite(out)] = np.nan
        elif a in ("median", "mode"):
            out = np.full(G, np.nan)
            dd = np.where(ok, d, np.nan)      # NA codes filter out too
            order = np.argsort(inv, kind="stable")
            bounds = np.searchsorted(inv[order], np.arange(G + 1))
            for g in range(G):
                seg = dd[order[bounds[g]: bounds[g + 1]]]
                seg = seg[~np.isnan(seg)]
                if len(seg):
                    out[g] = np.median(seg) if a == "median" else \
                        np.bincount(seg.astype(np.int64)).argmax()
        names.append(f"{a}_{fr.names[col_i]}")
        vecs.append(Vec(out.astype(np.float32)))
    return Frame(names, vecs)


def _table(node, env):
    """(table fr [fr2] [dense]) — level cross-tabulation (AstTable; the
    client always appends the dense boolean: frame.py table())."""
    args = [_eval(a, env) for a in node[1:]]
    dense = True
    if args and not isinstance(args[-1], Frame):
        try:
            dense = bool(float(args[-1]))
        except (TypeError, ValueError):
            dense = bool(args[-1])
        args.pop()
    fr = _as_frame(args[0])
    v1 = fr.vecs[0]
    d1 = v1.to_numpy()
    two_col = fr.ncols > 1 or len(args) > 1
    if not two_col:
        vals, cnts = np.unique(d1[d1 >= 0] if v1.is_categorical else
                               d1[~np.isnan(d1)], return_counts=True)
        if v1.is_categorical:
            c1 = Vec(vals.astype(np.int32), T_CAT, domain=list(v1.domain))
        else:
            c1 = Vec(vals.astype(np.float32))
        return Frame([fr.names[0], "Count"],
                     [c1, Vec(cnts.astype(np.float32))])
    v2 = fr.vecs[1] if fr.ncols > 1 else _as_frame(args[1]).vecs[0]
    d2 = v2.to_numpy()
    ok = ((d1 >= 0) if v1.is_categorical else ~np.isnan(d1)) & \
        ((d2 >= 0) if v2.is_categorical else ~np.isnan(d2))
    pairs = np.stack([d1[ok], d2[ok]], axis=1)
    uniq, cnts = np.unique(pairs, axis=0, return_counts=True)
    if not dense and v1.is_categorical and v2.is_categorical:
        # sparse=FALSE: every level combination, zero counts included
        full = np.array([(a, b) for a in range(len(v1.domain))
                         for b in range(len(v2.domain))], np.float64)
        cmap = {(a, b): c for (a, b), c in
                zip(map(tuple, uniq), cnts)}
        uniq = full
        cnts = np.array([cmap.get(tuple(p), 0) for p in full])
    c1 = Vec(uniq[:, 0].astype(np.int32), T_CAT,
             domain=list(v1.domain)) if v1.is_categorical else \
        Vec(uniq[:, 0].astype(np.float32))
    c2 = Vec(uniq[:, 1].astype(np.int32), T_CAT,
             domain=list(v2.domain)) if v2.is_categorical else \
        Vec(uniq[:, 1].astype(np.float32))
    return Frame([fr.names[0], "col2", "Counts"],
                 [c1, c2, Vec(cnts.astype(np.float32))])


_STROPS = ("toupper", "tolower", "trim", "nchar", "length", "substring",
           "replacefirst", "replaceall", "sub", "gsub", "strsplit",
           "countmatches", "lstrip", "rstrip")


def _map_strings(v: Vec, fn):
    """Apply a str->str fn: T_STR maps values, T_CAT maps the DOMAIN
    (the reference's in-place domain rewrite, ast/prims/string)."""
    if v.type == T_STR:
        return Vec([None if x is None else fn(str(x))
                    for x in v.host_data], T_STR)
    if v.is_categorical:
        new_dom = [fn(d) for d in v.domain]
        # domains must stay unique: re-map codes if the fn collides labels
        uniq = sorted(set(new_dom))
        lut = {d: i for i, d in enumerate(uniq)}
        remap = np.asarray([lut[d] for d in new_dom], np.int32)
        codes = v.to_numpy()
        new_codes = np.where(codes >= 0, remap[np.clip(codes, 0, None)],
                             -1)
        return Vec(new_codes.astype(np.int32), T_CAT, domain=uniq)
    raise TypeError("string op on a numeric column")


def _string_op(op, node, env):
    fr = _as_frame(_eval(node[1], env))
    args = [_lit(x) if isinstance(x, tuple) else x for x in node[2:]]

    if op in ("nchar", "length"):
        def count_chars(v):
            if v.type == T_STR:
                return Vec(np.asarray(
                    [np.nan if x is None else len(str(x))
                     for x in v.host_data], np.float32))
            lens = np.asarray([len(d) for d in v.domain], np.float32)
            codes = v.to_numpy()
            return Vec(np.where(codes >= 0,
                                lens[np.clip(codes, 0, None)],
                                np.nan).astype(np.float32))
        return Frame(list(fr.names), [count_chars(v) for v in fr.vecs])

    if op == "substring":
        lo = int(args[0])
        hi = int(args[1]) if len(args) > 1 and args[1] is not None else None
        fn = lambda s: s[lo:hi]  # noqa: E731
    elif op in ("replacefirst", "sub"):
        pat, rep = str(args[0]), str(args[1])
        fn = lambda s: re.sub(pat, rep, s, count=1)  # noqa: E731
    elif op in ("replaceall", "gsub"):
        pat, rep = str(args[0]), str(args[1])
        fn = lambda s: re.sub(pat, rep, s)  # noqa: E731
    elif op == "trim":
        fn = str.strip
    elif op == "lstrip":
        chars = str(args[0]) if args else None
        fn = lambda s: s.lstrip(chars)  # noqa: E731
    elif op == "rstrip":
        chars = str(args[0]) if args else None
        fn = lambda s: s.rstrip(chars)  # noqa: E731
    elif op == "toupper":
        fn = str.upper
    elif op == "tolower":
        fn = str.lower
    elif op == "countmatches":
        pat = str(args[0])

        def count_matches(v):
            def cm(s):
                return s.count(pat)
            if v.type == T_STR:
                return Vec(np.asarray(
                    [np.nan if x is None else cm(str(x))
                     for x in v.host_data], np.float32))
            per_level = np.asarray([cm(d) for d in v.domain], np.float32)
            codes = v.to_numpy()
            return Vec(np.where(codes >= 0,
                                per_level[np.clip(codes, 0, None)],
                                np.nan).astype(np.float32))
        return Frame(list(fr.names), [count_matches(v) for v in fr.vecs])
    elif op == "strsplit":
        pat = str(args[0])
        v = fr.vecs[0]
        vals = [None if x is None else re.split(pat, str(x))
                for x in (v.host_data if v.type == T_STR else
                          [v.domain[c] if c >= 0 else None
                           for c in v.to_numpy()])]
        width = max((len(x) for x in vals if x), default=1)
        cols = []
        for j in range(width):
            col = [x[j] if x and j < len(x) else None for x in vals]
            dom = sorted({c for c in col if c is not None})
            lut = {d: i for i, d in enumerate(dom)}
            cols.append(Vec(np.asarray(
                [lut.get(c, -1) if c is not None else -1 for c in col],
                np.int32), T_CAT, domain=dom))
        return Frame([f"C{j+1}" for j in range(width)], cols)
    else:
        raise NotImplementedError(op)
    return Frame(list(fr.names), [_map_strings(v, fn) for v in fr.vecs])


def _time_part(op, node, env):
    """Time extractors over T_TIME ms-since-epoch columns."""
    fr = _as_frame(_eval(node[1], env))
    out = []
    for v in fr.vecs:
        ms = np.asarray(v.to_numpy(), np.float64)
        ok = ~np.isnan(ms)
        dt = np.full(len(ms), np.datetime64("NaT"), "datetime64[ms]")
        dt[ok] = np.asarray(ms[ok], "int64").view("datetime64[ms]")
        Y = dt.astype("datetime64[Y]")
        M = dt.astype("datetime64[M]")
        D = dt.astype("datetime64[D]")
        if op == "year":
            vals = Y.astype(int) + 1970
        elif op == "month":
            vals = (M - Y).astype(int) + 1
        elif op == "day":
            vals = (D - M).astype(int) + 1
        elif op == "dayOfWeek":
            vals = (D.astype(int) + 3) % 7          # 1970-01-01 = Thursday
        elif op == "hour":
            vals = (dt - D).astype("timedelta64[h]").astype(int)
        elif op == "minute":
            vals = ((dt - D).astype("timedelta64[m]").astype(int)) % 60
        elif op == "second":
            vals = ((dt - D).astype("timedelta64[s]").astype(int)) % 60
        else:                                       # week of year
            vals = (D - Y).astype(int) // 7 + 1
        out.append(Vec(np.where(ok, vals, np.nan).astype(np.float32)))
    return Frame(list(fr.names), out)


def _update(node, env):
    """(:= fr rhs col_idxs row_sel) — in-place column/cell update."""
    fr = _dense(_as_frame(_eval(node[1], env)))
    rhs = _eval(node[2], env)
    if isinstance(rhs, Frame):
        _dense(rhs)
    cols = _col_indices(fr, node[3] if isinstance(node[3], tuple)
                        else _eval(node[3], env))
    row_sel = node[4] if len(node) > 4 else None
    out = Frame(list(fr.names), list(fr.vecs))
    for k, j in enumerate(cols):
        old_vec = out.vecs[j]
        if isinstance(rhs, Frame):
            newv = rhs.vecs[k if rhs.ncols > 1 else 0]
        elif old_vec.is_categorical:
            newv = Vec(np.full(fr.nrows, int(rhs), np.int32), T_CAT,
                       domain=list(old_vec.domain))
        else:
            newv = Vec(np.full(fr.nrows, float(rhs), np.float32))
        all_rows = row_sel is None or (
            isinstance(row_sel, tuple) and
            (row_sel[1] == "all" or row_sel[1] == []))  # [] = every row
        if not all_rows:
            sel = _eval(row_sel, env) if isinstance(row_sel, list) \
                else row_sel
            old = old_vec.to_numpy().astype(np.float64)
            if isinstance(sel, Frame):
                _dense(sel)
                mask = np.asarray(sel.vecs[0].data)[: fr.nrows] > 0
            else:
                if isinstance(sel, tuple):
                    idx = _expand_numlist(
                        sel[1] if sel[0] == "numlist" else [sel])
                else:
                    idx = [int(sel)]
                mask = np.zeros(fr.nrows, bool)
                mask[idx] = True
            nv = np.asarray(newv.to_numpy(), np.float64)
            merged = old.copy()
            n_sel = int(mask.sum())
            if len(nv) == fr.nrows:
                merged[mask] = nv[mask]
            elif len(nv) == n_sel:
                # rhs sized to the selection: scatter in selection order
                merged[np.flatnonzero(mask)] = nv
            else:
                merged[mask] = nv[0] if len(nv) else np.nan
            if old_vec.is_categorical:
                newv = Vec(merged.astype(np.int32), T_CAT,
                           domain=list(old_vec.domain))
            else:
                newv = Vec(merged.astype(np.float32), old_vec.type)
        out.vecs[j] = newv
    return out


def _impute(node, env):
    """(h2o.impute fr col method combine_method [gb_cols] ...) — mean/
    median/mode imputation (ast/prims/advmath/AstImpute)."""
    fr = _dense(_as_frame(_eval(node[1], env)))
    col = int(_eval(node[2], env))
    method = _lit(node[3]) if len(node) > 3 else "mean"
    v = fr.vecs[col]
    d = np.asarray(v.to_numpy(), np.float64)
    if v.is_categorical:
        vals = d[d >= 0]
        fill = float(np.bincount(vals.astype(np.int64)).argmax()) \
            if len(vals) else -1
        filled = np.where(d < 0, fill, d)
        newv = Vec(filled.astype(np.int32), T_CAT, domain=list(v.domain))
    else:
        vals = d[~np.isnan(d)]
        if method == "median":
            fill = float(np.median(vals)) if len(vals) else np.nan
        elif method == "mode":
            if len(vals):
                uq, cn = np.unique(vals, return_counts=True)
                fill = float(uq[cn.argmax()])
            else:
                fill = np.nan
        else:
            fill = float(vals.mean()) if len(vals) else np.nan
        filled = np.where(np.isnan(d), fill, d)
        newv = Vec(filled.astype(np.float32), v.type)
    out = Frame(list(fr.names), list(fr.vecs))
    out.vecs[col] = newv
    return out


# ---------------------------------------------------------------------------
# extended prim set — closes the client-emittable op inventory
# (reference: water/rapids/ast/prims/**; client call sites cited per op)
# ---------------------------------------------------------------------------

def _strlist(sel) -> List[str]:
    """A numlist of string literals (client-sent column-name lists)."""
    if sel is None:
        return []
    if isinstance(sel, tuple) and sel[0] == "numlist":
        return [_lit(x) for x in sel[1]]
    if isinstance(sel, tuple) and sel[0] == "str":
        return [sel[1]]
    return [sel]


def _col_sel_indices(fr: Frame, sel) -> List[int]:
    """Column selector that accepts indices OR names."""
    if isinstance(sel, tuple) and sel[0] == "numlist":
        items = sel[1]
        if items and isinstance(items[0], tuple) and items[0][0] == "str":
            return [fr.names.index(_lit(x)) for x in items]
        return _expand_numlist(items)
    if isinstance(sel, tuple) and sel[0] == "str":
        return [fr.names.index(sel[1])]
    if isinstance(sel, float):
        return [int(sel)]
    raise TypeError(f"bad column selector {sel}")


def _labels_of(v: Vec) -> List[Optional[str]]:
    """Row label strings of a str/categorical column."""
    if v.type == T_STR:
        return [None if x is None else str(x) for x in v.host_data]
    if v.is_categorical:
        dom = v.domain or []
        return [dom[int(c)] if c >= 0 else None
                for c in np.asarray(v.to_numpy())[: v.nrows]]
    raise TypeError("expected a string/categorical column")


def _op_scale(node, env):
    """(scale fr center scale) — AstScale; center/scale are bools or
    per-column numlists (h2o-py frame.py:4260)."""
    fr = _as_frame(_eval(node[1], env))

    def spec(arg, defaults):
        if isinstance(arg, tuple) and arg[0] == "numlist":
            return [float(x) for x in arg[1]]
        # evaluate so bare True/False id literals resolve to 1.0/0.0
        flag = _eval(arg, env)
        return defaults if flag else None
    num_idx = [j for j, v in enumerate(fr.vecs) if v.is_numeric]
    means = [float(fr.vecs[j].rollups.mean) for j in num_idx]
    sds = [float(fr.vecs[j].rollups.sigma) or 1.0 for j in num_idx]
    centers = spec(node[2], means)
    scales = spec(node[3], sds)
    out_vecs = list(fr.vecs)
    for k, j in enumerate(num_idx):
        x = fr.vecs[j].as_float()
        if centers is not None:
            x = x - centers[k]
        if scales is not None:
            x = x / (scales[k] or 1.0)
        out_vecs[j] = Vec(x, nrows=fr.nrows)
    return Frame(list(fr.names), out_vecs)


def _op_hist(node, env):
    """(hist x breaks) — AstHist.java:38-123; output frame
    [breaks, counts, mids_true, mids] with a leading NA row."""
    fr = _as_frame(_eval(node[1], env))
    v = fr.vecs[0]
    if not v.is_numeric:
        raise ValueError("hist only applies to single numeric columns")
    d = np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
    d = d[~np.isnan(d)]
    a = node[2]
    if isinstance(a, tuple) and a[0] == "numlist":
        brks = np.asarray([float(x) for x in a[1]], np.float64)
    else:
        if isinstance(a, tuple) and a[0] == "str":
            algo = a[1].lower()
            n = len(d)
            if algo == "rice":
                nb = int(np.ceil(2 * n ** (1.0 / 3)))
            elif algo == "sqrt":
                nb = int(np.ceil(np.sqrt(n)))
            else:                      # sturges default
                nb = int(np.ceil(np.log2(n) + 1))
        else:
            nb = int(a)
        brks = np.linspace(d.min(), d.max(), nb + 1)
    counts, _ = np.histogram(d, bins=brks)
    mids = 0.5 * (brks[:-1] + brks[1:])
    mids_true = np.array([
        d[(d >= brks[i]) & (d <= brks[i + 1] if i == len(brks) - 2
                            else d < brks[i + 1])].mean()
        if counts[i] else np.nan for i in range(len(brks) - 1)])
    pad = np.nan
    return Frame(
        ["breaks", "counts", "mids_true", "mids"],
        [Vec(brks.astype(np.float32)),
         Vec(np.concatenate([[pad], counts]).astype(np.float32)),
         Vec(np.concatenate([[pad], mids_true]).astype(np.float32)),
         Vec(np.concatenate([[pad], mids]).astype(np.float32))])


def _op_runif(node, env):
    """(h2o.runif fr seed) — AstRunif (h2o-py frame.py:4612)."""
    fr = _as_frame(_eval(node[1], env))
    seed = int(_eval(node[2], env))
    rng = np.random.default_rng(None if seed < 0 else seed)
    return Frame(["rnd"], [Vec(rng.uniform(size=fr.nrows)
                               .astype(np.float32))])


def _op_kfold(node, env):
    """(kfold_column fr n seed) — random fold assignment 0..n-1."""
    fr = _as_frame(_eval(node[1], env))
    n = int(_eval(node[2], env))
    seed = int(_eval(node[3], env)) if len(node) > 3 else -1
    rng = np.random.default_rng(None if seed < 0 else seed)
    return Frame(["fold"], [Vec(rng.integers(0, n, fr.nrows)
                                .astype(np.float32))])


def _op_modulo_kfold(node, env):
    fr = _as_frame(_eval(node[1], env))
    n = int(_eval(node[2], env))
    return Frame(["fold"], [Vec((np.arange(fr.nrows) % n)
                                .astype(np.float32))])


def _op_stratified_kfold(node, env):
    """(stratified_kfold_column y n seed) — per-class round-robin so every
    fold sees every level (AstStratifiedKFold)."""
    fr = _as_frame(_eval(node[1], env))
    n = int(_eval(node[2], env))
    seed = int(_eval(node[3], env)) if len(node) > 3 else -1
    y = np.asarray(fr.vecs[0].to_numpy())[: fr.nrows]
    rng = np.random.default_rng(None if seed < 0 else seed)
    fold = np.zeros(fr.nrows, np.float32)
    for k in np.unique(y[~np.isnan(y.astype(np.float64))]):
        idx = np.flatnonzero(y == k)
        rng.shuffle(idx)
        fold[idx] = np.arange(len(idx)) % n
    return Frame(["fold"], [Vec(fold)])


def _op_as_date(node, env):
    """(as.Date fr format) — string/factor column -> ms since epoch
    (T_TIME), java SimpleDateFormat-ish patterns mapped to strptime."""
    from h2o_tpu.core.frame import T_TIME
    import datetime as _dt
    fr = _as_frame(_eval(node[1], env))
    fmt = _lit(node[2])
    py_fmt = (str(fmt).replace("yyyy", "%Y").replace("yy", "%y")
              .replace("MM", "%m").replace("dd", "%d")
              .replace("HH", "%H").replace("mm", "%M").replace("ss", "%S"))
    out = []
    for v in fr.vecs:
        if v.type == T_TIME or (v.is_numeric and not v.is_categorical):
            # already epoch-ms (the parser types ISO dates as T_TIME):
            # truncate to the day boundary
            ms_arr = np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
            day = np.floor(ms_arr / 86400000.0) * 86400000.0
            out.append(Vec(np.where(np.isnan(ms_arr), np.nan, day),
                           T_TIME))
            continue
        ms = []
        for s in _labels_of(v):
            if s is None:
                ms.append(np.nan)
                continue
            try:
                dt = _dt.datetime.strptime(s, py_fmt)
                ms.append(dt.replace(tzinfo=_dt.timezone.utc)
                          .timestamp() * 1000.0)
            except ValueError:
                ms.append(np.nan)
        out.append(Vec(np.asarray(ms, np.float64), T_TIME))
    return Frame(list(fr.names), out)


def _mktime_like(node, env, zero_based: bool):
    """(mktime y M d H m s ms) / (moment ...) — args are scalars or
    1-col frames; returns ms since epoch.  mktime's month/day are 0-based
    (AstMktime.java), moment's are 1-based (AstMoment / h2o-py
    frame.py:1385 passes calendar values raw)."""
    import datetime as _dt
    args = [_eval(a, env) for a in node[1:8]]
    while len(args) < 7:
        args.append(0.0)
    nrows = max([a.nrows for a in args if isinstance(a, Frame)],
                default=1)

    def col(a, default=0.0):
        if isinstance(a, Frame):
            return np.asarray(a.vecs[0].to_numpy(), np.float64)[:nrows]
        return np.full(nrows, float(a) if a is not None else default)
    y, mo, d, h, mi, s, ms = (col(a) for a in args)
    off = 1 if zero_based else 0
    out = np.full(nrows, np.nan)
    for i in range(nrows):
        try:
            dt = _dt.datetime(int(y[i]), int(mo[i]) + off,
                              int(d[i]) + off, int(h[i]), int(mi[i]),
                              int(s[i]), tzinfo=_dt.timezone.utc)
            out[i] = dt.timestamp() * 1000.0 + float(ms[i])
        except (ValueError, OverflowError):
            out[i] = np.nan
    from h2o_tpu.core.frame import T_TIME
    return Frame(["mktime"], [Vec(out, T_TIME)])


def _op_which_extreme(op, node, env):
    """(which.max fr skipna axis) — AstWhichMax/Min: axis 0 = per-column
    row index (1-row frame), axis 1 = per-row column index (1-col frame);
    skipna=False lets NAs poison the result (h2o-py frame.py:4712-4756)."""
    fr = _as_frame(_eval(node[1], env))
    skipna = bool(_eval(node[2], env)) if len(node) > 2 else True
    axis = int(_eval(node[3], env)) if len(node) > 3 else 0
    arg = np.nanargmax if op == "which.max" else np.nanargmin
    arg_strict = np.argmax if op == "which.max" else np.argmin
    if axis == 1:
        num = [j for j, v in enumerate(fr.vecs) if v.is_numeric]
        mat = np.stack([np.asarray(fr.vecs[j].to_numpy(),
                                   np.float64)[: fr.nrows]
                        for j in num], axis=1)
        nanrow = np.isnan(mat).all(axis=1) if skipna \
            else np.isnan(mat).any(axis=1)
        safe = np.where(np.isnan(mat), -np.inf if op == "which.max"
                        else np.inf, mat) if skipna else mat
        idx = arg_strict(safe, axis=1).astype(np.float64)
        return Frame(["which.max" if op == "which.max" else "which.min"],
                     [Vec(np.where(nanrow, np.nan, idx))])
    vecs, names = [], []
    for n, v in zip(fr.names, fr.vecs):
        if not v.is_numeric:
            continue
        names.append(n)
        d = np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
        if np.isnan(d).all() or (not skipna and np.isnan(d).any()):
            vecs.append(Vec(np.asarray([np.nan], np.float32)))
            continue
        idx = arg(d)
        vecs.append(Vec(np.asarray([float(idx)], np.float32)))
    return Frame(names, vecs)


def _op_topn(node, env):
    """(topn fr col nPercent grabTopN) — AstTopN: [row index, value] of
    the top/bottom nPercent of a column (grabTopN: 1 top, -1 bottom)."""
    fr = _as_frame(_eval(node[1], env))
    col = int(_eval(node[2], env))
    npct = float(_eval(node[3], env))
    top = int(_eval(node[4], env)) >= 0
    d = np.asarray(fr.vecs[col].to_numpy(), np.float64)[: fr.nrows]
    ok = np.flatnonzero(~np.isnan(d))
    k = max(1, int(round(npct / 100.0 * len(ok))))
    order = ok[np.argsort(d[ok], kind="stable")]
    chosen = order[-k:][::-1] if top else order[:k]
    return Frame(
        ["Row Indices", fr.names[col]],
        [Vec(chosen.astype(np.float64)),
         Vec(d[chosen].astype(np.float32))])


def _op_grep(node, env):
    """(grep fr regex ignore_case invert output_logical) —
    ast/prims/string/AstGrep (h2o-py frame.py:4195)."""
    fr = _as_frame(_eval(node[1], env))
    pat = _lit(node[2])
    icase = bool(int(_eval(node[3], env))) if len(node) > 3 else False
    invert = bool(int(_eval(node[4], env))) if len(node) > 4 else False
    logical = bool(int(_eval(node[5], env))) if len(node) > 5 else False
    rx = re.compile(str(pat), re.IGNORECASE if icase else 0)
    labels = _labels_of(fr.vecs[0])
    hit = np.asarray([bool(rx.search(s)) if s is not None else False
                      for s in labels])
    if invert:
        hit = ~hit
    if logical:
        return Frame([fr.names[0]], [Vec(hit.astype(np.float32))])
    return Frame([fr.names[0]],
                 [Vec(np.flatnonzero(hit).astype(np.float64))])


def _op_strlen(node, env):
    fr = _as_frame(_eval(node[1], env))
    out = []
    for v in fr.vecs:
        out.append(Vec(np.asarray(
            [np.nan if s is None else len(s) for s in _labels_of(v)],
            np.float32)))
    return Frame(list(fr.names), out)


def _op_fillna(node, env):
    """(h2o.fillna fr method axis maxlen) — AstFillNA forward/backward
    fill along rows (axis 0) or columns (axis 1)."""
    fr = _as_frame(_eval(node[1], env))
    method = str(_lit(node[2])).lower()
    axis = int(_eval(node[3], env))
    maxlen = int(_eval(node[4], env))
    mat = np.stack([np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
                    for v in fr.vecs], axis=1)
    if axis == 1:
        mat = mat.T
    if method.startswith("back"):
        mat = mat[::-1]
    n, c = mat.shape
    run = np.zeros(c)
    last = np.full(c, np.nan)
    for i in range(n):
        row = mat[i]
        nan = np.isnan(row)
        run = np.where(nan, run + 1, 0)
        fill = nan & (run <= maxlen) & ~np.isnan(last)
        mat[i] = np.where(fill, last, row)
        last = np.where(np.isnan(mat[i]), last, mat[i])
    if method.startswith("back"):
        mat = mat[::-1]
    if axis == 1:
        mat = mat.T
    return Frame(list(fr.names),
                 [Vec(mat[:, j].astype(np.float32)) for j in
                  range(mat.shape[1])])


def _moments_reduce(op, node, env):
    """(skewness fr na_rm) / (kurtosis fr na_rm) — AstSkewness/AstKurtosis
    (sample skewness; kurtosis NOT excess, matches reference)."""
    fr = _as_frame(_eval(node[1], env))

    def red(v):
        d = np.asarray(v.to_numpy(), np.float64)[: v.nrows]
        d = d[~np.isnan(d)]
        n = len(d)
        if n < 2:
            return float("nan")
        m = d.mean()
        s2 = ((d - m) ** 2).sum() / (n - 1)
        if op == "skewness":
            return float(((d - m) ** 3).mean() / s2 ** 1.5)
        return float(((d - m) ** 4).mean() / s2 ** 2)
    return _reduce_all(red, fr)


def _op_dropdup(node, env):
    """(dropdup fr [cols] keep) — AstDropDuplicates
    (h2o-py frame.py:3234)."""
    fr = _as_frame(_eval(node[1], env))
    cols = _col_sel_indices(fr, node[2])
    keep = str(_lit(node[3])).strip().lower() if len(node) > 3 else "first"
    _, inv = _key_codes(fr, cols)
    keep_mask = np.zeros(fr.nrows, bool)
    if keep == "last":
        seen = {}
        for i, c in enumerate(inv):
            seen[c] = i
        keep_mask[list(seen.values())] = True
    else:
        seen = set()
        for i, c in enumerate(inv):
            if c not in seen:
                seen.add(c)
                keep_mask[i] = True
    return fr.slice_rows(keep_mask)


def _op_distance(node, env):
    """(distance x y measure) — AstDistance: pairwise distances, output
    (nrow_x, nrow_y) frame (h2o-py frame.py:3219)."""
    X = _as_frame(_eval(node[1], env))
    Y = _as_frame(_eval(node[2], env))
    measure = str(_lit(node[3])).lower()
    A = np.stack([np.asarray(v.to_numpy(), np.float64)[: X.nrows]
                  for v in X.vecs], axis=1)
    B = np.stack([np.asarray(v.to_numpy(), np.float64)[: Y.nrows]
                  for v in Y.vecs], axis=1)
    if measure in ("l2", "euclidean"):
        D = np.sqrt(np.maximum(
            (A * A).sum(1)[:, None] + (B * B).sum(1)[None, :]
            - 2 * A @ B.T, 0.0))
    elif measure in ("l1", "manhattan"):
        D = np.abs(A[:, None, :] - B[None, :, :]).sum(axis=2)
    elif measure in ("cosine", "cosine_sq"):
        na = np.linalg.norm(A, axis=1)
        nb = np.linalg.norm(B, axis=1)
        C = (A @ B.T) / np.maximum(na[:, None] * nb[None, :], 1e-12)
        D = C * C if measure == "cosine_sq" else C
    else:
        raise ValueError(f"unknown distance measure {measure!r}")
    return Frame([f"C{j+1}" for j in range(D.shape[1])],
                 [Vec(D[:, j].astype(np.float32))
                  for j in range(D.shape[1])])


def _op_melt(node, env):
    """(melt fr id_vars value_vars var_name value_name skipna) —
    AstMelt (h2o-py frame.py:3923)."""
    fr = _as_frame(_eval(node[1], env))
    id_idx = _col_sel_indices(fr, node[2])
    val_idx = _col_sel_indices(fr, node[3]) if not (
        isinstance(node[3], tuple) and node[3][0] == "numlist" and
        not node[3][1]) else \
        [j for j in range(fr.ncols) if j not in id_idx]
    var_name = _lit(node[4]) if len(node) > 4 else "variable"
    value_name = _lit(node[5]) if len(node) > 5 else "value"
    skipna = bool(int(_eval(node[6], env))) if len(node) > 6 else False
    n = fr.nrows
    var_dom = [fr.names[j] for j in val_idx]
    ids, var_col, val_col = [], [], []
    for k, j in enumerate(val_idx):
        d = np.asarray(fr.vecs[j].to_numpy(), np.float64)[:n]
        keep = ~np.isnan(d) if skipna else np.ones(n, bool)
        ids.append(np.flatnonzero(keep))
        var_col.append(np.full(int(keep.sum()), k, np.int32))
        val_col.append(d[keep])
    row_idx = np.concatenate(ids) if ids else np.zeros(0, np.int64)
    names, vecs = [], []
    for j in id_idx:
        v = fr.vecs[j]
        d = v.to_numpy()[row_idx]
        vecs.append(Vec(d.astype(np.int32), T_CAT, domain=list(v.domain))
                    if v.is_categorical else Vec(d, v.type))
        names.append(fr.names[j])
    names += [var_name, value_name]
    vecs += [Vec(np.concatenate(var_col) if var_col else
                 np.zeros(0, np.int32), T_CAT, domain=var_dom),
             Vec((np.concatenate(val_col) if val_col else
                  np.zeros(0)).astype(np.float32))]
    return Frame(names, vecs)


def _op_pivot(node, env):
    """(pivot fr index column value) — AstPivot
    (h2o-py frame.py:3891)."""
    fr = _as_frame(_eval(node[1], env))
    i_j = _col_sel_indices(fr, node[2])[0]
    c_j = _col_sel_indices(fr, node[3])[0]
    v_j = _col_sel_indices(fr, node[4])[0]
    iv, cv, vv = fr.vecs[i_j], fr.vecs[c_j], fr.vecs[v_j]
    ivals = np.asarray(iv.to_numpy(), np.float64)[: fr.nrows]
    cvals = np.asarray(cv.to_numpy(), np.float64)[: fr.nrows]
    vvals = np.asarray(vv.to_numpy(), np.float64)[: fr.nrows]
    uniq_i, inv_i = np.unique(ivals, return_inverse=True)
    uniq_c = np.unique(cvals[~np.isnan(cvals)])
    out = np.full((len(uniq_i), len(uniq_c)), np.nan)
    for r in range(fr.nrows):
        if np.isnan(cvals[r]):
            continue
        ci = np.searchsorted(uniq_c, cvals[r])
        out[inv_i[r], ci] = vvals[r]
    col_labels = ([cv.domain[int(c)] for c in uniq_c]
                  if cv.is_categorical else
                  [str(int(c)) if c == int(c) else str(c)
                   for c in uniq_c])
    names = [fr.names[i_j]] + list(col_labels)
    first = Vec(uniq_i.astype(np.int32), T_CAT, domain=list(iv.domain)) \
        if iv.is_categorical else Vec(uniq_i.astype(np.float32), iv.type)
    vecs = [first] + [Vec(out[:, k].astype(np.float32))
                      for k in range(len(uniq_c))]
    return Frame(names, vecs)


def _op_rank_within_gb(node, env):
    """(rank_within_groupby fr gb_cols sort_cols ascending new_col
    final_sort) — AstRankWithinGroupBy (h2o-py frame.py:3988): dense
    1-based rank within each group in sort order; NAs rank last."""
    fr = _as_frame(_eval(node[1], env))
    gcols = _col_sel_indices(fr, node[2])
    scols = _col_sel_indices(fr, node[3])
    asc = [bool(int(x)) for x in node[4][1]] if isinstance(node[4], tuple) \
        else [True] * len(scols)
    new_col = _lit(node[5]) if len(node) > 5 else "rank"
    final_sort = bool(int(_eval(node[6], env))) if len(node) > 6 else False
    _, ginv = _key_codes(fr, gcols)
    order = _sort_keys(fr, scols, asc)
    rank = np.zeros(fr.nrows, np.float32)
    counters: Dict[int, int] = {}
    for i in order:
        g = int(ginv[i])
        counters[g] = counters.get(g, 0) + 1
        rank[i] = counters[g]
    out = Frame(list(fr.names), list(fr.vecs))
    out.add(new_col, Vec(rank))
    if final_sort:
        out = out.slice_rows(_sort_keys(out, gcols + scols,
                                        [True] * len(gcols) + asc))
    return out


def _op_apply(node, env):
    """(apply fr margin { args . body }) — AstApply with the client's
    lambda AST (h2o-py frame.py:4806, astfun.lambda_to_expr)."""
    fr = _as_frame(_eval(node[1], env))
    margin = int(_eval(node[2], env))      # 1 = rows, 2 = columns
    rest = node[3:]
    if not (isinstance(rest[0], tuple) and _lit(rest[0]) == "{"):
        raise ValueError("apply expects a lambda { args . body }")
    i = 1
    args = []
    while _lit(rest[i]) != ".":
        args.append(_lit(rest[i]))
        i += 1
    body = rest[i + 1]
    if margin == 2:          # per column
        cols = []
        for j, v in enumerate(fr.vecs):
            sub = Frame([fr.names[j]], [v])
            env.locals[args[0]] = sub
            try:
                r = _eval(body, env)
            finally:
                env.locals.pop(args[0], None)
            if isinstance(r, Frame):
                cols.append((fr.names[j], r.vecs[0]))
            else:
                cols.append((fr.names[j],
                             Vec(np.asarray([float(r)], np.float32))))
        nr = max(v.nrows for _, v in cols)
        return Frame([n for n, _ in cols],
                     [v if v.nrows == nr else
                      Vec(np.resize(np.asarray(v.to_numpy()), nr))
                      for _, v in cols])
    # margin == 1: per row.  Fast path: a simple reducer body
    # "(reducer x)" computes along axis=1 directly — no transposition
    mat = np.stack([np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
                    for v in fr.vecs], axis=1)
    _ROW_REDUCERS = {"mean": np.nanmean, "sum": np.nansum,
                     "min": np.nanmin, "max": np.nanmax,
                     "median": np.nanmedian,
                     "sd": lambda m, axis: np.nanstd(m, axis=axis,
                                                     ddof=1)}
    if (isinstance(body, list) and len(body) == 2 and
            _lit(body[0]) in _ROW_REDUCERS and
            isinstance(body[1], tuple) and _lit(body[1]) == args[0]):
        vals = _ROW_REDUCERS[_lit(body[0])](mat, axis=1)
        return Frame(["apply"], [Vec(vals.astype(np.float32))])
    if fr.nrows > 100_000:
        raise ValueError(
            "apply(axis=1) with a non-reducer lambda materializes one "
            "column per row; limit is 100k rows — rewrite with "
            "column-wise ops or a supported row reducer "
            "(mean/sum/min/max/median/sd)")
    row_fr = Frame([f"C{i+1}" for i in range(mat.shape[0])],
                   [Vec(mat[i].astype(np.float32))
                    for i in range(mat.shape[0])])
    env.locals[args[0]] = row_fr
    try:
        r = _eval(body, env)
    finally:
        env.locals.pop(args[0], None)
    if isinstance(r, Frame):     # (nrows(fr) columns of len ncol) -> 1 col
        vals = [float(np.asarray(v.to_numpy())[0]) if v.nrows == 1 else
                np.nan for v in r.vecs]
        return Frame(["apply"], [Vec(np.asarray(vals, np.float32))])
    if isinstance(r, list):
        return Frame(["apply"], [Vec(np.asarray(r, np.float32))])
    return Frame(["apply"],
                 [Vec(np.full(fr.nrows, float(r), np.float32))])


def _op_set_level(node, env):
    """(setLevel fr 'level') — every row set to the given level
    (AstSetLevel; h2o-py frame.py:1466)."""
    fr = _as_frame(_eval(node[1], env))
    level = _lit(node[2])
    v = fr.vecs[0]
    if not v.is_categorical:
        raise ValueError("setLevel requires a categorical column")
    if level not in (v.domain or []):
        raise ValueError(f"level {level!r} not in domain")
    code = v.domain.index(level)
    return Frame(list(fr.names),
                 [Vec(np.full(fr.nrows, code, np.int32), T_CAT,
                      domain=list(v.domain))])


def _op_relevel(node, env):
    """(relevel fr 'y') — move level y to the front (AstRelevel)."""
    fr = _as_frame(_eval(node[1], env))
    level = _lit(node[2])
    v = fr.vecs[0]
    if not v.is_categorical or level not in (v.domain or []):
        raise ValueError(f"relevel: {level!r} not a level")
    new_dom = [level] + [d for d in v.domain if d != level]
    remap = _domain_remap(v.domain, new_dom)
    codes = np.asarray(v.to_numpy())
    new_codes = np.where(codes >= 0, remap[np.clip(codes, 0, None)], -1)
    return Frame(list(fr.names),
                 [Vec(new_codes.astype(np.int32), T_CAT, domain=new_dom)])


def _op_difflag1(node, env):
    """(difflag1 fr) — AstDiffLag1: x[i] - x[i-1]; first row NA."""
    fr = _as_frame(_eval(node[1], env))
    out = []
    for v in fr.vecs:
        d = np.asarray(v.to_numpy(), np.float64)[: fr.nrows]
        diff = np.concatenate([[np.nan], np.diff(d)])
        out.append(Vec(diff.astype(np.float32)))
    return Frame(list(fr.names), out)


def _op_prod(node, env, na_rm: bool):
    fr = _as_frame(_eval(node[1], env))

    def red(v):
        d = np.asarray(v.to_numpy(), np.float64)[: v.nrows]
        if na_rm:
            d = d[~np.isnan(d)]
        return float(np.prod(d))
    return _reduce_all(red, fr)


def _op_any_all(node, env, which: str):
    fr = _as_frame(_eval(node[1], env))
    vals = []
    for v in fr.vecs:
        d = np.asarray(v.to_numpy(), np.float64)[: v.nrows]
        d = d[~np.isnan(d)]
        vals.append(bool((d != 0).any() if which == "any"
                         else (d != 0).all()))
    return float(any(vals) if which == "any" else all(vals))


def _numlist_vals(node, env):
    return [float(x) for x in _mixed_list(node, env)]


def _mixed_list(node, env):
    """Bracket-list AST -> python values (strings kept as strings)."""
    if isinstance(node, tuple) and node[0] == "numlist":
        out = []
        for item in node[1]:
            if isinstance(item, tuple) and item[0] == "span":
                lo, hi = item[1], item[2]
                out.extend(float(x) for x in range(int(lo), int(hi) + 1))
            elif isinstance(item, tuple):
                out.append(item[1])
            else:
                out.append(item)
        return out
    if isinstance(node, tuple) and node[0] == "str":
        return [node[1]]
    ev = _eval(node, env)
    if ev is None:
        return []
    if isinstance(ev, (int, float, str)):
        return [ev]
    return list(ev)


def _op_not(node, env):
    fr = _eval(node[1], env)
    if isinstance(fr, (int, float)):
        return float(not fr)
    fr = _as_frame(fr)
    vecs = []
    for v in fr.vecs:
        d = v.as_float()
        vecs.append(Vec(jnp.where(jnp.isnan(d), jnp.nan,
                                  (d == 0).astype(jnp.float32)),
                        nrows=v.nrows))
    return Frame(list(fr.names), vecs)


def _op_as_character(node, env):
    """(as.character fr) — categorical/numeric -> string column."""
    fr = _as_frame(_eval(node[1], env))
    vecs = []
    for v in fr.vecs:
        if v.type == T_STR:
            vecs.append(v)
        elif v.is_categorical:
            vecs.append(Vec(_labels_of(v), T_STR))
        else:
            arr = v.to_numpy()
            vecs.append(Vec([None if np.isnan(x) else
                             (str(int(x)) if float(x).is_integer()
                              else repr(float(x))) for x in arr], T_STR))
    return Frame(list(fr.names), vecs)


def _op_is_character(node, env):
    fr = _as_frame(_eval(node[1], env))
    return [float(v.type == T_STR) for v in fr.vecs]


def _op_any_factor(node, env):
    fr = _as_frame(_eval(node[1], env))
    return float(any(v.is_categorical for v in fr.vecs))


def _op_any_na(node, env):
    fr = _as_frame(_eval(node[1], env))
    for v in fr.vecs:
        if v.host_data is not None:
            if any(x is None for x in v.host_data):
                return 1.0
        elif v.nacnt() > 0:
            return 1.0
    return 0.0


def _op_match(node, env):
    """(match fr table nomatch None) — AstMatch: position of each value
    in `table` (1-based like R), nomatch fill."""
    fr = _as_frame(_eval(node[1], env))
    table = _mixed_list(node[2], env)
    nomatch = _eval(node[3], env)
    nomatch = float("nan") if nomatch is None else float(nomatch)
    v = fr.vecs[0]
    if v.is_categorical or v.type == T_STR:
        labels = _labels_of(v)
        lut = {}
        for i, t in enumerate(table):      # first occurrence wins (R)
            lut.setdefault(str(t), i + 1)
        out = np.asarray([lut.get(s, nomatch) if s is not None
                          else nomatch for s in labels], np.float32)
    else:
        arr = v.to_numpy()
        lut = {}
        for i, t in enumerate(table):      # first occurrence wins (R)
            lut.setdefault(float(t), i + 1)
        out = np.asarray([lut.get(float(x), nomatch)
                          if not np.isnan(x) else nomatch
                          for x in arr], np.float32)
    return Frame(["match"], [Vec(out)])


def _rank_avg(col: np.ndarray) -> np.ndarray:
    """Average ranks (R ties.method="average"); NaNs stay NaN."""
    out = np.full(col.shape, np.nan)
    ok = ~np.isnan(col)
    v = col[ok]
    order = np.argsort(v, kind="stable")
    ranks = np.empty(len(v))
    ranks[order] = np.arange(1, len(v) + 1, dtype=np.float64)
    # average ranks over ties
    sv = v[order]
    i = 0
    while i < len(sv):
        j = i
        while j + 1 < len(sv) and sv[j + 1] == sv[i]:
            j += 1
        if j > i:
            ranks[order[i: j + 1]] = ranks[order[i: j + 1]].mean()
        i = j + 1
    out[ok] = ranks
    return out


def _op_cor(node, env):
    """(cor fr y use method) — AstCorrelation: Pearson or Spearman;
    use="everything" propagates NAs (NaN result), "complete.obs" drops
    NA rows (the R semantics the client forwards)."""
    fx = _as_frame(_eval(node[1], env))
    fy = _as_frame(_eval(node[2], env))
    use = str(_lit(node[3])).lower()
    method = str(_lit(node[4])).lower()
    X = np.asarray(fx.as_matrix())[: fx.nrows].astype(np.float64)
    Y = np.asarray(fy.as_matrix())[: fy.nrows].astype(np.float64)
    if use in ("complete.obs", "na.or.complete"):
        ok = ~(np.isnan(X).any(axis=1) | np.isnan(Y).any(axis=1))
        X, Y = X[ok], Y[ok]
    elif use != "everything":
        raise ValueError(f"cor: use={use!r} not supported "
                         "(everything, complete.obs)")
    if method == "spearman":
        X = np.stack([_rank_avg(X[:, j]) for j in range(X.shape[1])], 1)
        Y = np.stack([_rank_avg(Y[:, j]) for j in range(Y.shape[1])], 1)
    elif method != "pearson":
        raise ValueError(f"cor: method={method!r} not supported "
                         "(Pearson, Spearman)")
    # with use="everything", any NaN poisons the pairwise sums -> NaN,
    # matching R
    Xc = X - X.mean(axis=0)
    Yc = Y - Y.mean(axis=0)
    denom = np.outer(np.sqrt((Xc ** 2).sum(axis=0)),
                     np.sqrt((Yc ** 2).sum(axis=0)))
    C = (Xc.T @ Yc) / np.maximum(denom, 1e-300)
    if C.size == 1:
        return float(C[0, 0])
    return Frame(list(fy.names),
                 [Vec(C[:, j].astype(np.float32)) for j in
                  range(C.shape[1])])


def _op_cut(node, env):
    """(cut fr breaks labels include_lowest right dig_lab) — AstCut."""
    fr = _as_frame(_eval(node[1], env))
    breaks = _numlist_vals(node[2], env)
    labels = [str(s) for s in _mixed_list(node[3], env)] or None
    include_lowest = bool(_eval(node[4], env))
    right = bool(_eval(node[5], env))
    dig = int(_eval(node[6], env))
    v = fr.vecs[0]
    x = np.asarray(v.to_numpy(), np.float64)
    b = np.asarray(breaks, np.float64)
    if right:
        codes = np.searchsorted(b, x, side="left") - 1
        if include_lowest:
            codes = np.where(x == b[0], 0, codes)
    else:
        codes = np.searchsorted(b, x, side="right") - 1
        codes = np.where(x == b[-1], len(b) - 2, codes)
    codes = np.where(np.isnan(x) | (codes < 0) | (codes > len(b) - 2),
                     -1, codes).astype(np.int32)
    n_bins_expected = len(b) - 1
    if labels and len(labels) != n_bins_expected:
        raise ValueError(
            f"cut: {len(labels)} labels for {n_bins_expected} bins")
    if not labels:
        fmt = f"%.{dig}g"
        lb, rb = ("(", "]") if right else ("[", ")")
        labels = [f"{lb}{fmt % b[i]},{fmt % b[i+1]}{rb}"
                  for i in range(len(b) - 1)]
        if include_lowest and right:
            labels[0] = "[" + labels[0][1:]
    return Frame(list(fr.names),
                 [Vec(codes, T_CAT, domain=[str(s) for s in labels])])


def _op_entropy(node, env):
    """(entropy fr) — per-string Shannon entropy over characters."""
    import math as _m
    fr = _as_frame(_eval(node[1], env))
    out = []
    for v in fr.vecs:
        vals = []
        for s in _labels_of(v):
            if s is None or not s:
                vals.append(np.nan if s is None else 0.0)
                continue
            counts = {}
            for ch in s:
                counts[ch] = counts.get(ch, 0) + 1
            n = len(s)
            vals.append(-sum(c / n * _m.log2(c / n)
                             for c in counts.values()))
        out.append(Vec(np.asarray(vals, np.float32)))
    return Frame(list(fr.names), out)


def _op_columns_by_type(node, env):
    """(columnsByType fr coltype) — 1-based column indices by kind."""
    fr = _as_frame(_eval(node[1], env))
    want = str(_lit(node[2])).lower()
    idx = []
    for j, v in enumerate(fr.vecs):
        ok = {"numeric": v.is_numeric, "categorical": v.is_categorical,
              "string": v.type == T_STR, "time": v.type == "time",
              "uuid": v.type == "uuid",
              "bad": v.type == "bad"}.get(want, False)
        if ok:
            idx.append(float(j))
    return idx


def _op_filter_na_cols(node, env):
    """(filterNACols fr frac) — 1-based indices of columns with <= frac
    NAs (AstFilterNaCols)."""
    fr = _as_frame(_eval(node[1], env))
    frac = float(_eval(node[2], env))
    out = []
    for j, v in enumerate(fr.vecs):
        nac = (sum(x is None for x in v.host_data)
               if v.host_data is not None else v.nacnt())
        if nac <= frac * fr.nrows:
            out.append(float(j + 1))
    return out


def _op_ls(node, env):
    from h2o_tpu.core.cloud import cloud
    keys = sorted(str(k) for k in cloud().dkv.keys())
    dom = keys or ["<empty>"]
    return Frame(["key"], [Vec(np.arange(len(dom), dtype=np.int32),
                               T_CAT, domain=dom)])


def _op_getrow(node, env):
    """(getrow fr) — 1xN frame -> N-element value list (AstGetrow
    returns ValRow even for N=1: the client always subscripts, e.g.
    frame.mean()[0] — _explain.py model_correlation)."""
    fr = _as_frame(_eval(node[1], env))
    if fr.nrows != 1:
        raise ValueError("getrow works on single-row frames only")
    return [float(np.asarray(v.as_float())[0]) for v in fr.vecs]


def _op_flatten(node, env):
    fr = _eval(node[1], env)
    if isinstance(fr, (int, float)):
        return float(fr)
    fr = _as_frame(fr)
    v = fr.vecs[0]
    if v.type == T_STR:
        return str(v.host_data[0])
    if v.is_categorical:
        lab = _labels_of(v)[0]
        return str(lab) if lab is not None else float("nan")
    return float(np.asarray(v.as_float())[0])


def _op_rep_len(node, env):
    """(rep_len x length) — recycle values to a target length."""
    src = _eval(node[1], env)
    length = int(_eval(node[2], env))
    if isinstance(src, (int, float)):
        vals = np.full(length, float(src), np.float32)
        return Frame(["C1"], [Vec(vals)])
    fr = _as_frame(src)
    arr = np.asarray(fr.vecs[0].to_numpy())
    reps = int(np.ceil(length / max(len(arr), 1)))
    out = np.tile(arr, reps)[:length].astype(np.float32)
    v = fr.vecs[0]
    if v.is_categorical:
        return Frame([fr.names[0]], [Vec(out.astype(np.int32), T_CAT,
                                         domain=list(v.domain))])
    return Frame([fr.names[0]], [Vec(out)])


def _op_transpose(node, env):
    fr = _as_frame(_eval(node[1], env))
    X = np.asarray(fr.as_matrix())[: fr.nrows].T
    return Frame([f"C{i+1}" for i in range(X.shape[1])],
                 [Vec(X[:, j].astype(np.float32))
                  for j in range(X.shape[1])])


def _op_sumaxis(node, env):
    """(sumaxis fr skipna axis) — axis 0: per-col sums; 1: per-row."""
    fr = _as_frame(_eval(node[1], env))
    skipna = bool(_eval(node[2], env))
    axis = int(_eval(node[3], env))
    X = np.asarray(fr.as_matrix())[: fr.nrows].astype(np.float64)
    f = np.nansum if skipna else np.sum
    if axis == 0:
        return [float(f(X[:, j])) for j in range(X.shape[1])]
    return Frame(["sum"], [Vec(f(X, axis=1).astype(np.float32))])


def _op_str_distance(node, env):
    """(strDistance fr y measure compare_empty) — Levenshtein / lcs /
    jaccard string distances (AstStrDistance)."""
    fx = _as_frame(_eval(node[1], env))
    fy = _as_frame(_eval(node[2], env))
    measure = str(_lit(node[3])).lower()
    cmp_empty = bool(_eval(node[4], env))

    def lev(a, b):
        if a == b:
            return 0.0
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return float(prev[-1])

    def lcs_len(a, b):
        prev = [0] * (len(b) + 1)
        for ca in a:
            cur = [0]
            for j, cb in enumerate(b, 1):
                cur.append(prev[j - 1] + 1 if ca == cb
                           else max(prev[j], cur[j - 1]))
            prev = cur
        return prev[-1]

    def dist(a, b):
        if a is None or b is None:
            return np.nan
        if (not a or not b) and not cmp_empty:
            return np.nan
        if measure in ("lv", "levenshtein"):
            return lev(a, b)
        if measure == "lcs":
            return float(len(a) + len(b) - 2 * lcs_len(a, b))
        if measure == "jaccard":
            sa, sb = set(a), set(b)
            u = sa | sb
            return 1.0 - (len(sa & sb) / len(u) if u else 1.0)
        raise ValueError(f"strDistance measure {measure!r} not "
                         "supported (lv, lcs, jaccard)")

    la = _labels_of(fx.vecs[0])
    lb = _labels_of(fy.vecs[0])
    vals = np.asarray([dist(a, b) for a, b in zip(la, lb)], np.float32)
    return Frame(["distance"], [Vec(vals)])


def _op_tokenize(node, env):
    """(tokenize fr split) — AstTokenize: split every string cell into
    one long word column with an NA row after each source row (the
    word2vec ingest shape)."""
    fr = _as_frame(_eval(node[1], env))
    split = str(_lit(node[2]))
    import re as _re
    rx = _re.compile(split)
    col_labels = [_labels_of(v) if v.type == T_STR or v.is_categorical
                  else None for v in fr.vecs]
    out: List[Optional[str]] = []
    for i in range(fr.nrows):
        for labels in col_labels:
            s = labels[i] if labels is not None else None
            if s:
                out.extend(t for t in rx.split(s) if t)
        out.append(None)
    return Frame(["C1"], [Vec(out, T_STR)])


def _op_list_timezones(node, env):
    import zoneinfo
    zones = sorted(zoneinfo.available_timezones())
    return Frame(["Timezones"],
                 [Vec(np.arange(len(zones), dtype=np.int32), T_CAT,
                      domain=zones)])


def _op_set_timezone(node, env):
    """(setTimeZone "tz") — AstSetTimeZone: the cluster timezone used to
    interpret wall-clock date strings at parse time (ParseTime.
    setTimezone); h2o.init() itself issues this (h2o.py:293)."""
    import zoneinfo
    tz = _lit(node[1])
    if tz not in zoneinfo.available_timezones():
        raise ValueError(
            f"Unacceptable timezone {tz} given.  For a list of "
            "acceptable names, use listTimezone().")
    from h2o_tpu.core.cloud import cloud
    cloud().timezone = tz
    return tz


def _op_get_timezone(node, env):
    """(getTimeZone) — AstGetTimeZone."""
    from h2o_tpu.core.cloud import cloud
    return getattr(cloud(), "timezone", None) or "UTC"


def _op_set_domain(node, env):
    """(setDomain fr in_place [labels]) — replace a cat column's levels."""
    fr = _as_frame(_eval(node[1], env))
    labels = _mixed_list(node[3], env)
    v = fr.vecs[0]
    if not v.is_categorical:
        raise ValueError("setDomain needs a categorical column")
    if len(labels) < len(v.domain or []):
        raise ValueError(f"setDomain: {len(labels)} labels for "
                         f"{len(v.domain or [])} levels")
    nv = Vec(np.asarray(v.to_numpy(), np.int32), T_CAT,
             domain=[str(s) for s in labels])
    return Frame(list(fr.names), [nv])


def _op_append_levels(node, env):
    fr = _as_frame(_eval(node[1], env))
    labels = _mixed_list(node[3], env)
    v = fr.vecs[0]
    if not v.is_categorical:
        raise ValueError("appendLevels needs a categorical column")
    dom = list(v.domain or [])
    for lab in labels:
        if str(lab) not in dom:
            dom.append(str(lab))
    return Frame(list(fr.names),
                 [Vec(np.asarray(v.to_numpy(), np.int32), T_CAT,
                      domain=dom)])


def _op_relevel_by_freq(node, env):
    """(relevel.by.freq fr weights top_n) — reorder levels most-frequent
    first (top_n = -1: all)."""
    fr = _as_frame(_eval(node[1], env))
    wcol = _lit(node[2])
    top_n = int(_eval(node[3], env))
    out_vecs = []
    for v in fr.vecs:
        if not v.is_categorical:
            out_vecs.append(v)
            continue
        codes = np.asarray(v.to_numpy(), np.int64)
        w = np.ones(len(codes))
        if wcol and wcol in fr.names:
            w = np.asarray(fr.vec(wcol).to_numpy(), np.float64)
        card = len(v.domain or [])
        freq = np.zeros(card)
        ok = codes >= 0
        np.add.at(freq, codes[ok], w[ok])
        order = np.argsort(-freq, kind="stable")
        if top_n > 0:
            head = order[:top_n]
            tail = np.asarray([i for i in np.arange(card)
                               if i not in set(head.tolist())])
            order = np.concatenate([head, tail]) if len(tail) else head
        remap = np.empty(card, np.int64)
        remap[order] = np.arange(card)
        new_codes = np.where(ok, remap[np.clip(codes, 0, card - 1)],
                             -1).astype(np.int32)
        out_vecs.append(Vec(new_codes, T_CAT,
                            domain=[str(v.domain[i]) for i in order]))
    return Frame(list(fr.names), out_vecs)


def _op_week(node, env):
    fr = _as_frame(_eval(node[1], env))
    import datetime as _dt
    out = []
    for v in fr.vecs:
        ms = np.asarray(v.to_numpy(), np.float64)
        vals = [np.nan if np.isnan(x) else float(
            _dt.datetime.utcfromtimestamp(x / 1000.0)
            .isocalendar()[1]) for x in ms]
        out.append(Vec(np.asarray(vals, np.float32)))
    return Frame(list(fr.names), out)


def _op_num_valid_substrings(node, env):
    """(num_valid_substrings fr path) — count substrings present in the
    words file (AstCountSubstringsWords)."""
    fr = _as_frame(_eval(node[1], env))
    path = str(_lit(node[2]))
    with open(path) as f:
        words = {ln.strip() for ln in f if ln.strip()}
    out = []
    for v in fr.vecs:
        vals = []
        for s in _labels_of(v):
            if s is None:
                vals.append(np.nan)
                continue
            cnt = 0
            for i in range(len(s)):
                for j in range(i + 1, len(s) + 1):
                    if s[i:j] in words:
                        cnt += 1
            vals.append(float(cnt))
        out.append(Vec(np.asarray(vals, np.float32)))
    return Frame(list(fr.names), out)


def _op_w2v_to_frame(node, env):
    """(word2vec.to.frame model) — embeddings as [Word, V1..VD]."""
    from h2o_tpu.core.cloud import cloud
    from h2o_tpu.models.word2vec import Word2VecModel
    m = cloud().dkv.get(_lit(node[1]))
    if not isinstance(m, Word2VecModel):
        raise ValueError(f"no word2vec model {_lit(node[1])!r}")
    words = list(m.output["words"])
    W = np.asarray(m.output["vectors"], np.float32)
    vecs = [Vec(np.arange(len(words), dtype=np.int32), T_CAT,
                domain=[str(w) for w in words])]
    names = ["Word"] + [f"V{i+1}" for i in range(W.shape[1])]
    vecs += [Vec(W[:, j]) for j in range(W.shape[1])]
    return Frame(names, vecs)


def _op_rulefit_predict_rules(node, env):
    """(rulefit.predict.rules model fr [rule_ids]) — per-row rule
    validity flags (RuleFit predict_rules)."""
    from h2o_tpu.core.cloud import cloud
    m = cloud().dkv.get(_lit(node[1]))
    fr = _as_frame(_eval(node[2], env))
    rule_ids = [str(s) for s in _mixed_list(node[3], env)]
    if m is None or m.output.get("rule_importance") is None:
        raise ValueError("rulefit.predict.rules needs a RuleFit model")
    rows = {str(r[0]): r for r in m.output["rule_importance"]}
    out_names, out_vecs = [], []
    for rid in rule_ids:
        if rid not in rows:
            raise ValueError(f"unknown rule id {rid!r}")
        rule_txt = str(rows[rid][3])
        mask = _eval_rule_text(rule_txt, fr)
        out_names.append(rid)
        out_vecs.append(Vec(mask.astype(np.float32)))
    return Frame(out_names, out_vecs)


def _eval_rule_text(rule: str, fr: Frame) -> np.ndarray:
    """Evaluate a rendered RuleFit rule ('a < 1.5 & b >= 2' style
    conjunctions, 'x in {l1, l2}' for categoricals) over a frame."""
    mask = np.ones(fr.nrows, bool)
    for cond in rule.split("&"):
        cond = cond.strip()
        if not cond or cond.lower() == "linear":
            continue
        m_in = re.match(r"(\S+)\s+in\s+\{([^}]*)\}", cond)
        if m_in:
            col, levels = m_in.group(1), [s.strip() for s in
                                          m_in.group(2).split(",")]
            v = fr.vec(col)
            labs = _labels_of(v)
            mask &= np.asarray([s in levels if s is not None else False
                                for s in labs])
            continue
        m_cmp = re.match(r"(\S+)\s*(<=|>=|<|>)\s*(-?[\d.eE+]+)", cond)
        if m_cmp:
            col, op_s, thr = m_cmp.groups()
            x = np.asarray(fr.vec(col).to_numpy(), np.float64)
            thr = float(thr)
            cmp = {"<": x < thr, "<=": x <= thr, ">": x > thr,
                   ">=": x >= thr}[op_s]
            mask &= np.where(np.isnan(x), False, cmp)
            continue
        raise ValueError(f"cannot evaluate rule fragment {cond!r}")
    return mask


def _op_tf_idf(node, env):
    """(tf-idf fr doc_id_idx text_idx preprocess case_sensitive) —
    hex/tfidf/{TermFrequency,InverseDocumentFrequency}Task; client
    h2o.information_retrieval.tf_idf.  Output rows:
    [DocID, Word, TF, IDF, TF-IDF] with IDF = log((N+1)/(df+1))."""
    import math
    from collections import Counter
    fr = _as_frame(_eval(node[1], env))
    doc_i = int(_eval(node[2], env))
    text_i = int(_eval(node[3], env))
    preprocess = bool(_eval(node[4], env))
    case_sensitive = bool(_eval(node[5], env))
    dv, tv = fr.vecs[doc_i], fr.vecs[text_i]
    doc_ids = dv.to_numpy()
    if tv.host_data is not None:
        texts = ["" if s is None else str(s) for s in tv.host_data]
    elif tv.is_categorical:
        dom = tv.domain or []
        texts = [dom[int(c)] if c >= 0 else "" for c in tv.to_numpy()]
    else:
        raise ValueError("tf-idf wants a string/categorical text column")
    tf: Counter = Counter()
    doc_words: Dict = {}
    for d, t in zip(doc_ids, texts):
        d = float(d)
        if not case_sensitive:
            t = t.lower()
        words = t.split() if preprocess else ([t] if t else [])
        for w in words:
            tf[(d, w)] += 1
            doc_words.setdefault(w, set()).add(d)
    n_docs = len(set(float(d) for d in doc_ids))
    rows = sorted(tf.items())
    out_doc = np.asarray([d for (d, _w), _c in rows], np.float32)
    words_dom = sorted(doc_words)
    widx = {w: i for i, w in enumerate(words_dom)}
    out_word = np.asarray([widx[w] for (_d, w), _c in rows], np.int32)
    out_tf = np.asarray([c for _k, c in rows], np.float32)
    out_idf = np.asarray(
        [math.log((n_docs + 1.0) / (len(doc_words[w]) + 1.0))
         for (_d, w), _c in rows], np.float32)
    return Frame(
        ["DocID", "Word", "TF", "IDF", "TF_IDF"],
        [Vec(out_doc), Vec(out_word, T_CAT, domain=words_dom),
         Vec(out_tf), Vec(out_idf), Vec(out_tf * out_idf)])


def _model_arg(node, env):
    from h2o_tpu.core.cloud import cloud
    mid = str(_lit(node))
    m = cloud().dkv.get(mid)
    if m is None:
        raise ValueError(f"model {mid!r} not found")
    return m


def _op_reset_threshold(node, env):
    """(model.reset.threshold model thr) — set the binomial label
    threshold, return the old one (AstModelResetThreshold; client
    h2o.utils.model_utils.reset_model_threshold)."""
    m = _model_arg(node[1], env)
    thr = float(_eval(node[2], env))
    old = float(m.output.get("default_threshold", 0.5))
    if not 0.0 < thr < 1.0:
        raise ValueError(f"threshold must be in (0,1), got {thr}")
    m.output["default_threshold"] = thr
    return Frame(["threshold"],
                 [Vec(np.asarray([old], np.float32))])


def _op_permutation_varimp(node, env):
    """(PermutationVarImp model fr metric n_samples n_repeats features
    seed) — permutation importance (ref hex/PermutationVarImp.java):
    metric degradation when one column is shuffled."""
    from h2o_tpu.models.score_keeper import (is_maximizing,
                                             resolve_stopping_metric)
    m = _model_arg(node[1], env)
    fr = _as_frame(_eval(node[2], env))
    metric = str(_lit(node[3]) or "AUTO")
    n_samples = int(_eval(node[4], env) or -1)
    n_repeats = max(int(_eval(node[5], env) or 1), 1)
    feats_node = node[6]
    features = [str(s) for s in _mixed_list(feats_node, env)] \
        if not (isinstance(feats_node, tuple) and
                feats_node[0] == "id" and feats_node[1] == "None") else []
    seed = int(_eval(node[7], env) or -1)
    rng = np.random.default_rng(seed if seed >= 0 else None)

    work = fr
    if 0 < n_samples < fr.nrows:
        idx = rng.choice(fr.nrows, size=n_samples, replace=False)
        work = fr.slice_rows(np.sort(idx))
    x = [c for c in m.output.get("x", []) if c in work.names]
    features = features or x

    def metric_of(frame) -> float:
        mm = m.model_metrics(frame)
        name = metric
        if name.upper() == "AUTO":
            name = resolve_stopping_metric("AUTO", mm.kind)
        v = mm.get(name)
        if v is None:
            v = mm.get(name.upper())
        if v is None:
            v = mm.get(name.lower())
        if v is None:
            raise ValueError(f"metric {metric!r} not available")
        return float(v), name

    base, name = metric_of(work)
    maximize = is_maximizing(name)
    rows = []
    for c in features:
        drops = []
        for _ in range(n_repeats):
            shuf = Frame(list(work.names), list(work.vecs))
            v = work.vec(c)
            perm = rng.permutation(work.nrows)
            arr = v.to_numpy()[perm]
            shuf.vecs[work.names.index(c)] = Vec(
                np.asarray(arr, np.int32), T_CAT,
                domain=list(v.domain)) if v.is_categorical else Vec(
                np.asarray(arr, np.float32), v.type)
            pv, _ = metric_of(shuf)
            drops.append((base - pv) if maximize else (pv - base))
        rows.append((c, [max(d, 0.0) for d in drops]))
    if n_repeats > 1:
        names = ["Variable"] + [f"Run {i+1}" for i in range(n_repeats)]
        dom = [r[0] for r in rows]
        vecs = [Vec(np.arange(len(dom), dtype=np.int32), T_CAT,
                    domain=dom)]
        for i in range(n_repeats):
            vecs.append(Vec(np.asarray([r[1][i] for r in rows],
                                       np.float32)))
        return Frame(names, vecs)
    rel = np.asarray([r[1][0] for r in rows], np.float64)
    mx, tot = max(rel.max(), 1e-30), max(rel.sum(), 1e-30)
    dom = [r[0] for r in rows]
    return Frame(
        ["Variable", "Relative Importance", "Scaled Importance",
         "Percentage"],
        [Vec(np.arange(len(dom), dtype=np.int32), T_CAT, domain=dom),
         Vec(rel.astype(np.float32)),
         Vec((rel / mx).astype(np.float32)),
         Vec((rel / tot).astype(np.float32))])


def _op_pred_vs_actual_by_var(node, env):
    """(predicted.vs.actual.by.var model fr variable predicted) — mean
    predicted vs actual per level of `variable`."""
    m = _model_arg(node[1], env)
    fr = _as_frame(_eval(node[2], env))
    var = str(_lit(node[3]))
    pf = _as_frame(_eval(node[4], env))
    if var not in fr.names:
        raise ValueError(f"column {var!r} not in frame")
    y = m.params.get("response_column")
    v = fr.vec(var)
    if not v.is_categorical:
        raise ValueError("predicted.vs.actual.by.var wants a "
                         "categorical variable")
    codes = np.asarray(v.to_numpy(), np.int64)
    pred = np.asarray(pf.vecs[-1].to_numpy(), np.float64)[: fr.nrows]
    yv = fr.vec(y)
    act = np.asarray(yv.as_float() if yv.is_categorical
                     else yv.to_numpy(), np.float64)[: fr.nrows]
    card = len(v.domain or [])
    rows_p, rows_a = [], []
    for k in range(card):
        sel = codes == k
        okp = sel & ~np.isnan(pred)
        oka = sel & ~np.isnan(act)
        rows_p.append(float(pred[okp].mean()) if okp.any()
                      else float("nan"))
        rows_a.append(float(act[oka].mean()) if oka.any()
                      else float("nan"))
    return Frame(
        [var, "predicted", "actual"],
        [Vec(np.arange(card, dtype=np.int32), T_CAT,
             domain=list(v.domain)),
         Vec(np.asarray(rows_p, np.float32)),
         Vec(np.asarray(rows_a, np.float32))])


def _op_fairness_metrics(node, env):
    """(fairnessMetrics model fr protected_cols reference
    favorable_class) — per-group confusion/rate metrics plus adverse-
    impact ratios vs the reference group (ref hex/AstFairnessMetrics)."""
    m = _model_arg(node[1], env)
    fr = _as_frame(_eval(node[2], env))
    prot = [str(s) for s in _mixed_list(node[3], env)]
    ref_levels = [str(s) for s in _mixed_list(node[4], env)]
    favorable = str(_lit(node[5]))
    y = m.params.get("response_column")
    yv = fr.vec(y)
    dom = list(yv.domain or [])
    if len(dom) != 2:
        raise ValueError("fairnessMetrics supports binomial models "
                         f"(response has {len(dom)} levels)")
    if favorable not in dom:
        raise ValueError(f"favorable_class {favorable!r} not in response "
                         f"domain {dom}")
    fav = dom.index(favorable)
    raw = np.asarray(m.predict_raw(fr))[: fr.nrows]
    # raw[:, 0] is the model's own thresholded label — selection must
    # agree with what the model predicts, whichever class is favorable
    pred_fav = raw[:, 0].astype(np.int64) == fav
    act = np.asarray(yv.to_numpy(), np.int64)
    act_fav = act == fav

    groups = [np.asarray(fr.vec(c).to_numpy(), np.int64) for c in prot]
    doms = [list(fr.vec(c).domain or []) for c in prot]
    import itertools as _it
    combos = list(_it.product(*[range(len(d)) for d in doms]))

    def rates(sel):
        n = int(sel.sum())
        if n == 0:
            return None
        tp = int((sel & pred_fav & act_fav).sum())
        fp = int((sel & pred_fav & ~act_fav).sum())
        fn = int((sel & ~pred_fav & act_fav).sum())
        tn = int((sel & ~pred_fav & ~act_fav).sum())
        return dict(total=n, tp=tp, fp=fp, fn=fn, tn=tn,
                    selected=(tp + fp) / n,
                    tpr=tp / max(tp + fn, 1), fpr=fp / max(fp + tn, 1),
                    accuracy=(tp + tn) / n)
    ref_sel = np.ones(fr.nrows, bool)
    if ref_levels:
        for g, d, lev in zip(groups, doms, ref_levels):
            ref_sel &= g == (d.index(lev) if lev in d else -2)
    ref_r = rates(ref_sel) or dict(selected=1.0, tpr=1.0, fpr=1.0,
                                   accuracy=1.0, total=0, tp=0, fp=0,
                                   fn=0, tn=0)
    cols: Dict[str, list] = {c: [] for c in prot}
    met: Dict[str, list] = {k: [] for k in
                            ("total", "tp", "fp", "fn", "tn",
                             "selectedRatio", "tpr", "fpr", "accuracy",
                             "AIR_selectedRatio")}
    for combo in combos:
        sel = np.ones(fr.nrows, bool)
        for g, k in zip(groups, combo):
            sel &= g == k
        r = rates(sel)
        if r is None:
            continue
        for c, k, d in zip(prot, combo, doms):
            cols[c].append(d[k])
        met["total"].append(r["total"])
        for k in ("tp", "fp", "fn", "tn"):
            met[k].append(r[k])
        met["selectedRatio"].append(r["selected"])
        met["tpr"].append(r["tpr"])
        met["fpr"].append(r["fpr"])
        met["accuracy"].append(r["accuracy"])
        met["AIR_selectedRatio"].append(
            r["selected"] / max(ref_r["selected"], 1e-12))
    names, vecs = [], []
    for c in prot:
        d = sorted(set(cols[c]))
        vecs.append(Vec(np.asarray([d.index(v) for v in cols[c]],
                                   np.int32), T_CAT, domain=d))
        names.append(c)
    for k, vals in met.items():
        names.append(k)
        vecs.append(Vec(np.asarray(vals, np.float32)))
    return Frame(names, vecs)


def _op_isax(node, env):
    """(isax fr num_words max_cardinality optimize_card) — AstIsax:
    per-row z-normalize, PAA into num_words segments, symbolize against
    gaussian breakpoints; emits the iSAX word string per row."""
    from scipy.special import ndtri  # inverse normal CDF (scipy is baked)
    fr = _as_frame(_eval(node[1], env))
    num_words = int(_eval(node[2], env))
    card = int(_eval(node[3], env))
    optimize_card = bool(_eval(node[4], env))
    if optimize_card:
        raise NotImplementedError(
            "isax optimize_card=True (per-word cardinality reduction) is "
            "not implemented; pass optimize_card=False")
    if num_words <= 0 or card <= 1:
        raise ValueError("isax: num_words > 0 and max_cardinality > 1")
    X = np.asarray(fr.as_matrix())[: fr.nrows].astype(np.float64)
    R, C = X.shape
    mu = np.nanmean(X, axis=1, keepdims=True)
    sd = np.nanstd(X, axis=1, keepdims=True)
    Z = (X - mu) / np.where(sd == 0, 1.0, sd)
    # PAA: average over num_words equal segments
    edges = np.linspace(0, C, num_words + 1).astype(int)
    paa = np.stack([np.nanmean(Z[:, edges[i]: max(edges[i + 1],
                                                  edges[i] + 1)], axis=1)
                    for i in range(num_words)], axis=1)
    brk = ndtri(np.arange(1, card) / card)     # card-1 breakpoints
    sym = np.searchsorted(brk, np.nan_to_num(paa))     # (R, W) in [0,card)
    words = ["_".join(f"{int(s)}^{card}" for s in row) for row in sym]
    dom = sorted(set(words))
    codes = np.asarray([dom.index(w) for w in words], np.int32)
    return Frame(["iSAX_index"], [Vec(codes, T_CAT, domain=dom)])


def _op_make_leaderboard(node, env):
    """(makeLeaderboard [model_ids] lb_frame_key sort_metric
    extra_columns scoring_data) — h2o.make_leaderboard (client
    scoring.py:62; ref water/rapids/prims/AstMakeLeaderboard)."""
    from h2o_tpu.core.cloud import cloud
    from h2o_tpu.models.leaderboard import Leaderboard
    ids = [str(s) for s in _mixed_list(node[1], env)]
    lb_key = str(_lit(node[2]) or "")
    sort_metric = str(_lit(node[3]) or "AUTO")
    extra_cols = [str(s).lower() for s in _mixed_list(node[4], env)]
    scoring_data = str(_lit(node[5]) or "AUTO").lower()
    lb_frame = cloud().dkv.get(lb_key) if lb_key else None
    models = []
    for mid in ids:
        m = cloud().dkv.get(mid)
        if m is None:
            raise ValueError(f"makeLeaderboard: model {mid!r} not found")
        models.append(m)
    lb = Leaderboard(sort_metric=None if sort_metric.upper() == "AUTO"
                     else sort_metric.lower(),
                     leaderboard_frame=lb_frame,
                     scoring_data=scoring_data)
    lb.add(*models)
    rows = lb.rows()
    if not rows:
        raise ValueError("makeLeaderboard: no models")
    if "all" in extra_cols:
        extra_cols = ["training_time_ms", "predict_time_per_row_ms",
                      "algo"]
    if "predict_time_per_row_ms" in extra_cols:
        import time as _time
        for r_, m in zip(rows, lb.sorted_models()):
            score_fr = lb_frame or cloud().dkv.get(
                str(m.params.get("training_frame") or ""))
            if score_fr is None:
                r_["predict_time_per_row_ms"] = float("nan")
                continue
            t0 = _time.perf_counter()
            np.asarray(m.predict_raw(score_fr))
            r_["predict_time_per_row_ms"] = (
                (_time.perf_counter() - t0) * 1000.0 /
                max(score_fr.nrows, 1))
    drop = {"algo"} - set(extra_cols)
    if "training_time_ms" not in extra_cols:
        drop.add("training_time_ms")
    names = [k for k in rows[0] if k not in drop]
    vecs = []
    out_names = []
    for nname in names:
        vals = [r[nname] for r in rows]
        if nname in ("model_id", "algo"):
            dom = [str(v) for v in vals]
            # domains must be unique-sorted; codes map row -> label
            uniq = sorted(set(dom))
            codes = np.asarray([uniq.index(v) for v in dom], np.int32)
            vecs.append(Vec(codes, T_CAT, domain=uniq))
        else:
            vecs.append(Vec(np.asarray(
                [np.nan if v is None else float(v) for v in vals],
                np.float32)))
        out_names.append(nname)
    return Frame(out_names, vecs)


def _op_segment_models_as_frame(node, env):
    """(segment_models_as_frame sm_id) — AstSegmentModelsAsFrame
    (h2o-py segment_models.py:48): tabular view of a SegmentModels
    collection."""
    from h2o_tpu.core.cloud import cloud
    from h2o_tpu.models.segment import SegmentModels
    key = _lit(node[1])
    sm = cloud().dkv.get(key)
    if not isinstance(sm, SegmentModels):
        raise ValueError(f"no segment models under key {key}")
    return sm.to_frame()


_EXTRA_OPS = {
    "tf-idf": _op_tf_idf,
    "segment_models_as_frame": _op_segment_models_as_frame,
    "makeLeaderboard": _op_make_leaderboard,
    "model.reset.threshold": _op_reset_threshold,
    "PermutationVarImp": _op_permutation_varimp,
    "predicted.vs.actual.by.var": _op_pred_vs_actual_by_var,
    "fairnessMetrics": _op_fairness_metrics,
    "isax": _op_isax,
    "not": _op_not,
    "as.character": _op_as_character,
    "is.character": _op_is_character,
    "any.factor": _op_any_factor,
    "any.na": _op_any_na,
    "match": _op_match,
    "cor": _op_cor,
    "cut": _op_cut,
    "entropy": _op_entropy,
    "columnsByType": _op_columns_by_type,
    "filterNACols": _op_filter_na_cols,
    "ls": _op_ls,
    "getrow": _op_getrow,
    "flatten": _op_flatten,
    "rep_len": _op_rep_len,
    "t": _op_transpose,
    "sumaxis": _op_sumaxis,
    "strDistance": _op_str_distance,
    "tokenize": _op_tokenize,
    "listTimeZones": _op_list_timezones,
    "setTimeZone": _op_set_timezone,
    "getTimeZone": _op_get_timezone,
    "setDomain": _op_set_domain,
    "appendLevels": _op_append_levels,
    "relevel.by.freq": _op_relevel_by_freq,
    "week": _op_week,
    "num_valid_substrings": _op_num_valid_substrings,
    "word2vec.to.frame": _op_w2v_to_frame,
    "rulefit.predict.rules": _op_rulefit_predict_rules,
    "scale": _op_scale,
    "hist": _op_hist,
    "h2o.runif": _op_runif,
    "kfold_column": _op_kfold,
    "modulo_kfold_column": _op_modulo_kfold,
    "stratified_kfold_column": _op_stratified_kfold,
    "as.Date": _op_as_date,
    "mktime": lambda n, e: _mktime_like(n, e, zero_based=True),
    "moment": lambda n, e: _mktime_like(n, e, zero_based=False),
    "which.max": lambda n, e: _op_which_extreme("which.max", n, e),
    "which.min": lambda n, e: _op_which_extreme("which.min", n, e),
    "topn": _op_topn,
    "grep": _op_grep,
    "strlen": _op_strlen,
    "h2o.fillna": _op_fillna,
    "fillna": _op_fillna,
    "skewness": lambda n, e: _moments_reduce("skewness", n, e),
    "kurtosis": lambda n, e: _moments_reduce("kurtosis", n, e),
    "dropdup": _op_dropdup,
    "distance": _op_distance,
    "melt": _op_melt,
    "pivot": _op_pivot,
    "rank_within_groupby": _op_rank_within_gb,
    "apply": _op_apply,
    "setLevel": _op_set_level,
    "relevel": _op_relevel,
    "difflag1": _op_difflag1,
    "prod": lambda n, e: _op_prod(n, e, na_rm=False),
    "prod.na": lambda n, e: _op_prod(n, e, na_rm=True),
    "any": lambda n, e: _op_any_all(n, e, "any"),
    "all": lambda n, e: _op_any_all(n, e, "all"),
}


_OP_NAMES_CACHE: Optional[List[str]] = None


def op_names() -> List[str]:
    """Every op name the interpreter dispatches (GET /99/Rapids/help;
    reference water/api/RapidsHelpHandler lists rapids/ast/prims/**).
    Computed once; falls back to the table-registered ops when source
    isn't available (bytecode-only install)."""
    global _OP_NAMES_CACHE
    if _OP_NAMES_CACHE is not None:
        return _OP_NAMES_CACHE
    names = set(_BINOPS) | set(_UNOPS) | set(_CUMOPS) | set(_STROPS) | \
        set(_EXTRA_OPS)
    try:
        import re as _re
        with open(__file__) as f:
            src = f.read()
        names.update(_re.findall(r'op == "([^"]+)"', src))
        for grp in _re.findall(r'op in \(([^)]*)\)', src):
            names.update(_re.findall(r'"([^"]+)"', grp))
    except OSError:
        pass
    _OP_NAMES_CACHE = sorted(names)
    return _OP_NAMES_CACHE


_NUMPY_REPR = re.compile(
    r"np\.(?:str_|bytes_|float64|float32|float16|int64|int32|int16|int8|"
    r"intc|intp|uint64|uint32|uint16|uint8|longlong|bool_)\("
    r"('[^']*'|\"[^\"]*\"|[^()]*)\)")


def _normalize_numpy_reprs(expr: str) -> str:
    """numpy>=2 scalar reprs leak into client-built ASTs (h2o-py
    serializes np.str_ column selectors via repr — frame.py __getitem__
    + expr.py), arriving as ``np.str_('c')`` instead of ``'c'``.  Strip
    the wrapper so the stock client keeps working on numpy 2 images.

    Standalone quoted strings are left untouched: a user string
    argument that literally mentions ``np.float64(0)`` (e.g. a
    replaceall pattern) must not be rewritten.  A wrapper may itself
    QUOTE its argument (``np.str_('c')``) — that match starts at an
    unquoted position, so it still unwraps."""
    prev = None
    while prev != expr:                  # nested wrappers unwrap too
        prev = expr
        out = []
        i, n = 0, len(expr)
        while i < n:
            ch = expr[i]
            m = _NUMPY_REPR.match(expr, i)
            if m:
                out.append(m.group(1))
                i = m.end()
            elif ch in "'\"":            # copy the quoted span verbatim
                j = i + 1
                while j < n and expr[j] != ch:
                    j += 1
                out.append(expr[i: j + 1])
                i = j + 1
            else:
                out.append(ch)
                i += 1
        expr = "".join(out)
    return expr


def rapids_exec(expr: str, session: Optional[Session] = None):
    """Execute a Rapids expression string (the /3/Rapids POST body)."""
    session = session or Session()
    if "np." in expr:
        expr = _normalize_numpy_reprs(expr)
    return _eval(parse(expr), _Env(session))
