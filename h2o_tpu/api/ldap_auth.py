"""LDAP simple-bind authentication for the REST server.

Reference: the webserver's JAAS LdapLoginModule (-ldap_login +
login.conf; water/webserver + h2o-jetty security handlers).  The TPU
rebuild needs only the wire primitive the login module ultimately
performs — an LDAPv3 simple BIND — so it is implemented directly on a
socket with a minimal BER encoder (~none of python-ldap's surface is
required, and the image ships no LDAP library).
"""

from __future__ import annotations

import socket
from typing import Tuple


def escape_dn_value(value: str) -> str:
    """RFC 4514 §2.4 escaping for one attribute value inside a DN.

    Without this, a crafted HTTP Basic username like ``cn=svc,dc=x``
    substituted into the ldap_dn_template would alter the DN structure
    and bind outside the subtree the template constrains."""
    out = []
    for i, ch in enumerate(value):
        if ch in ',+"\\<>;=' or \
                (ch == " " and i in (0, len(value) - 1)) or \
                (ch == "#" and i == 0):
            out.append("\\" + ch)
        elif ord(ch) < 0x20:           # control chars -> hex pairs
            out.append("\\%02x" % ord(ch))
        else:
            out.append(ch)
    return "".join(out)


def _ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def _tlv(tag: int, payload: bytes) -> bytes:
    return bytes([tag]) + _ber_len(len(payload)) + payload


def _bind_request(dn: str, password: str, msg_id: int = 1) -> bytes:
    """LDAPMessage{ messageID, [APPLICATION 0] BindRequest{ version=3,
    name=dn, simple[0]=password } }."""
    bind = (_tlv(0x02, bytes([3])) +                 # version INTEGER 3
            _tlv(0x04, dn.encode()) +                # name OCTET STRING
            _tlv(0x80, password.encode()))           # simple [0]
    msg = _tlv(0x02, bytes([msg_id])) + _tlv(0x60, bind)
    return _tlv(0x30, msg)


def _read_tlv(buf: bytes, off: int) -> Tuple[int, bytes, int]:
    tag = buf[off]
    ln = buf[off + 1]
    off += 2
    if ln & 0x80:
        n = ln & 0x7F
        if off + n > len(buf):
            # long-form length straddles a TCP segment: decoding the
            # partial slice would yield a bogus length — signal
            # "incomplete" so the caller keeps buffering
            raise IndexError("partial BER length")
        ln = int.from_bytes(buf[off: off + n], "big")
        off += n
    return tag, buf[off: off + ln], off + ln


def ldap_bind(host: str, port: int, dn: str, password: str,
              timeout: float = 10.0, use_tls: bool = False) -> bool:
    """One LDAPv3 simple bind; True iff resultCode == success(0).

    Anonymous binds are refused up front: an empty password would
    'succeed' against most directories without proving anything (the
    classic unauthenticated-bind pitfall the JAAS module also guards).
    ``use_tls`` wraps the connection (ldaps://) with certificate
    verification; LDAP_TLS_NOVERIFY=1 disables verification for
    self-signed directories."""
    if not password:
        return False
    import os as _os
    raw = socket.create_connection((host, port), timeout=timeout)
    if use_tls:
        import ssl
        if _os.environ.get("LDAP_TLS_NOVERIFY") == "1":
            ctx = ssl._create_unverified_context()
        else:
            ctx = ssl.create_default_context()
        raw = ctx.wrap_socket(raw, server_hostname=host)
    with raw as s:
        s.sendall(_bind_request(dn, password))
        buf = b""
        while True:
            chunk = s.recv(4096)
            if not chunk:
                return False
            buf += chunk
            try:
                tag, msg, end = _read_tlv(buf, 0)
            except IndexError:
                continue                   # header not complete yet
            if end > len(buf):
                continue                   # payload not complete yet
            if tag != 0x30:
                return False
            try:
                # LDAPMessage: messageID, then BindResponse
                # [APPLICATION 1]
                _t, _mid, off = _read_tlv(msg, 0)
                rtag, resp, _ = _read_tlv(msg, off)
                if rtag != 0x61:
                    return False
                # BindResponse: resultCode ENUMERATED, matchedDN, diag
                ctag, code, _ = _read_tlv(resp, 0)
            except IndexError:
                return False           # malformed response -> deny
            return ctag == 0x0A and code == b"\x00"


def parse_ldap_url(url: str) -> Tuple[str, int, bool]:
    """ldap[s]://host[:port] -> (host, port, use_tls); default ports
    389/636.  Unknown schemes are refused — silently discarding an
    'ldaps' would downgrade the bind to plaintext."""
    scheme, sep, rest = url.partition("://")
    if not sep:
        scheme, rest = "ldap", url
    scheme = scheme.lower()
    if scheme not in ("ldap", "ldaps"):
        raise ValueError(f"unsupported LDAP scheme {scheme!r} in {url!r}"
                         " (use ldap:// or ldaps://)")
    tls = scheme == "ldaps"
    rest = rest.rstrip("/")
    if rest.startswith("["):           # bracketed IPv6 literal
        host, _, tail = rest[1:].partition("]")
        if tail.startswith(":"):
            return host, int(tail[1:]), tls
        return host, (636 if tls else 389), tls
    if ":" in rest:
        host, port = rest.rsplit(":", 1)
        return host, int(port), tls
    return rest, (636 if tls else 389), tls
