"""Grep-based lint: raw network I/O must go through the retry layer.

Every HTTP(S)/byte-store touch belongs behind core/persist.py's
read_bytes/write_bytes (retried, chaos-injectable, observable) — a bare
``urllib.request.urlopen`` anywhere else silently reopens the
one-shot-I/O hole this layer closed.  Allowed: persist.py (the scheme
backends themselves) and resilience.py (the wrapper's own plumbing,
should it ever need one).
"""

import ast
import os
import re

import h2o_tpu

ALLOWED = {os.path.join("core", "persist.py"),
           os.path.join("core", "resilience.py")}
PATTERN = re.compile(r"\burlopen\s*\(")


def test_no_bare_urlopen_outside_persist():
    pkg_root = os.path.dirname(h2o_tpu.__file__)
    offenders = []
    for dirpath, _dirs, files in os.walk(pkg_root):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, pkg_root)
            if rel in ALLOWED:
                continue
            with open(path, encoding="utf-8", errors="replace") as f:
                for i, line in enumerate(f, 1):
                    if PATTERN.search(line):
                        offenders.append(f"{rel}:{i}: {line.strip()}")
    assert not offenders, (
        "bare urlopen() outside the persist/retry layer — route these "
        "through h2o_tpu.core.persist.read_bytes/write_bytes (or add a "
        "scheme backend in persist.py) so transient faults retry:\n"
        + "\n".join(offenders))


# Per-request compiles must live behind serve/engine.py's bounded,
# bucket-keyed cache — a jax.jit in a REST handler compiles an XLA
# program per request shape and silently reopens the recompile storm the
# serving engine closed.
JIT_PATTERN = re.compile(r"\bjax\s*\.\s*jit\s*\(")
JIT_IMPORT = re.compile(r"^\s*from\s+jax\s+import\s+.*\bjit\b")


def test_no_jax_jit_in_api_handlers():
    pkg_root = os.path.dirname(h2o_tpu.__file__)
    api_dir = os.path.join(pkg_root, "api")
    offenders = []
    for name in sorted(os.listdir(api_dir)):
        if not (name.startswith("handlers") and name.endswith(".py")):
            continue
        path = os.path.join(api_dir, name)
        with open(path, encoding="utf-8", errors="replace") as f:
            for i, line in enumerate(f, 1):
                if JIT_PATTERN.search(line) or JIT_IMPORT.search(line):
                    offenders.append(f"api/{name}:{i}: {line.strip()}")
    assert not offenders, (
        "jax.jit inside api/handlers*.py — per-request compiles belong "
        "behind h2o_tpu/serve/engine.py's bounded compiled-predict "
        "cache (power-of-two batch buckets), not in REST handlers:\n"
        + "\n".join(offenders))


# jax.jit applied inside a function body wraps a freshly-created closure
# per call, so EVERY call re-traces and re-compiles — the anti-pattern
# the dispatch cache (core/mrtask.py) exists to kill.  Jitting belongs at
# module level (one executable per shape, process-wide) or behind a
# counted, bounded cache.  Allowed: the dispatch-cache module itself and
# the serving engine's bucket-keyed compiled-predict cache.
JIT_CLOSURE_ALLOWED = {os.path.join("core", "mrtask.py"),
                       os.path.join("serve", "engine.py"),
                       # munge kernel builders run ONLY under
                       # mrtask.cached_kernel (dispatch-cache miss =
                       # compile, counted) — one executable per
                       # (verb, schema, shape-bucket)
                       os.path.join("core", "munge.py"),
                       # jits live under functools.lru_cache(maxsize=32)
                       # keyed on (loss, regularizer) config — bounded
                       # once-per-config, not per-call
                       os.path.join("models", "glrm.py")}


def _is_jax_jit(node) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "jit" and
            isinstance(node.value, ast.Name) and node.value.id == "jax")


def _jit_in_function_bodies(tree):
    """Line numbers of ``jax.jit`` references inside function BODIES.
    A module-level ``@jax.jit`` decorator (or module-level assignment)
    evaluates once at import and is the CORRECT pattern — decorators are
    visited at their enclosing scope, not the function's body scope."""
    hits = []

    def visit(node, in_body):
        if _is_jax_jit(node) and in_body:
            hits.append(node.lineno)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                visit(dec, in_body)
            for child in node.body:
                visit(child, True)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, in_body)

    visit(tree, False)
    return hits


# The device-munge conversion (core/munge.py) eliminated per-row
# device->host pulls from the Rapids hot verbs.  A `to_numpy()` creeping
# back into a converted verb (or into the munge kernel layer itself)
# silently reopens the HBM->host->HBM round-trip this layer closed.
# Host fallbacks live in explicitly-suffixed `*_host` functions (the
# allowlist below) — new host-only ops go there, not in the dispatchers.
DEVICE_MUNGE_VERBS = {"_sort", "_merge", "_groupby", "_row_select"}
MUNGE_HOST_ALLOWED = {"_merge_host", "_groupby_host", "_row_select_host",
                      "_row_select_mask_host", "_sort_keys", "_key_codes"}


def _to_numpy_hits(tree, only_functions=None):
    """Line numbers of ``.to_numpy(`` calls, optionally restricted to
    the bodies of the named top-level functions."""
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if only_functions is not None and node.name not in only_functions:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Attribute) and sub.attr == "to_numpy":
                hits.append((node.name, sub.lineno))
    return hits


def test_no_to_numpy_in_device_munge_verbs():
    pkg_root = os.path.dirname(h2o_tpu.__file__)
    offenders = []
    interp = os.path.join(pkg_root, "rapids", "interp.py")
    with open(interp, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for fn, ln in _to_numpy_hits(tree, DEVICE_MUNGE_VERBS):
        offenders.append(f"rapids/interp.py:{ln} in {fn}()")
    munge = os.path.join(pkg_root, "core", "munge.py")
    with open(munge, encoding="utf-8") as f:
        mtree = ast.parse(f.read())
    for fn, ln in _to_numpy_hits(mtree):
        offenders.append(f"core/munge.py:{ln} in {fn}()")
    assert not offenders, (
        "to_numpy() inside a device-converted munge verb — these verbs "
        "must stay zero-host-pull.  Put host-only logic in the *_host "
        "fallbacks (rapids/interp.py) instead:\n" + "\n".join(offenders))


def test_munge_host_fallbacks_still_exist():
    """The host oracle is part of the contract (H2O_TPU_DEVICE_MUNGE=0
    must keep working) — renaming a fallback away breaks the parity
    suite's comparison baseline."""
    pkg_root = os.path.dirname(h2o_tpu.__file__)
    interp = os.path.join(pkg_root, "rapids", "interp.py")
    with open(interp, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    names = {n.name for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    missing = MUNGE_HOST_ALLOWED - names
    assert not missing, f"host munge fallbacks missing: {sorted(missing)}"


def test_no_jax_jit_on_local_closures():
    pkg_root = os.path.dirname(h2o_tpu.__file__)
    offenders = []
    for dirpath, _dirs, files in os.walk(pkg_root):
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, pkg_root)
            if rel in JIT_CLOSURE_ALLOWED:
                continue
            with open(path, encoding="utf-8", errors="replace") as f:
                try:
                    tree = ast.parse(f.read())
                except SyntaxError:
                    continue
            offenders.extend(f"{rel}:{ln}"
                             for ln in _jit_in_function_bodies(tree))
    assert not offenders, (
        "jax.jit referenced inside a function body — this wraps a fresh "
        "closure per call and re-compiles every time.  Move the jit to "
        "module level, or route through the dispatch cache "
        "(h2o_tpu/core/mrtask.py map_reduce/map_frame/mutate_array):\n"
        + "\n".join(sorted(set(offenders))))
