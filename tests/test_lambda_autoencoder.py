"""GLM lambda_search + DeepLearning autoencoder — the round-2 verdict's
"silent no-op params" (glm.py lambda_search/nlambdas/lambda_min_ratio,
deeplearning.py autoencoder) must actually work.

Reference: hex/glm/GLM.java:987-988,1236-1254 (lambda path);
hex/deeplearning autoencoder objective + H2OAutoEncoderModel.anomaly
(h2o-py/h2o/model/models/autoencoder.py:42).
"""

import numpy as np
import pytest

from h2o_tpu.core.frame import Frame, Vec, T_CAT


pytestmark = pytest.mark.slow   # compile-heavy (conftest tier doc)

def _sparse_binomial(rng, n=4000, p=20, informative=3):
    X = rng.normal(size=(n, p)).astype(np.float32)
    beta = np.zeros(p)
    beta[:informative] = [2.0, -1.5, 1.0]
    logits = X @ beta
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.int32)
    names = [f"x{j}" for j in range(p)] + ["y"]
    vecs = [Vec(X[:, j]) for j in range(p)] + \
        [Vec(y, T_CAT, domain=["n", "p"])]
    return Frame(names, vecs)


def test_glm_lambda_search_path(cl, rng):
    from h2o_tpu.models.glm import GLM
    tr = _sparse_binomial(rng)
    va = _sparse_binomial(rng)
    m = GLM(family="binomial", lambda_search=True, nlambdas=20,
            alpha=1.0, seed=7).train(y="y", training_frame=tr,
                                     validation_frame=va)
    out = m.output
    assert out["lambda_best"] is not None
    rp = out["reg_path"]
    assert 1 < len(rp["lambdas"]) <= 20
    # path is geometric-descending from lambda_max
    assert rp["lambdas"][0] == pytest.approx(out["lambda_max"])
    assert all(a > b for a, b in zip(rp["lambdas"], rp["lambdas"][1:]))
    # explained deviance improves along the path (less regularization)
    edt = rp["explained_deviance_train"]
    assert edt[-1] > edt[0]
    assert rp["explained_deviance_valid"] is not None
    assert len(rp["coefficients"]) == len(rp["lambdas"])
    # the selected model is predictive
    auc = m.output["training_metrics"]["AUC"]
    assert auc > 0.75
    # L1 at high lambda kills noise coefficients: first path entry sparser
    first = np.array(rp["coefficients"][0][:-1])
    last = np.array(rp["coefficients"][-1][:-1])
    assert (np.abs(first) > 1e-6).sum() <= (np.abs(last) > 1e-6).sum()


def test_glm_lambda_search_no_validation(cl, rng):
    from h2o_tpu.models.glm import GLM
    tr = _sparse_binomial(rng, n=1000)
    m = GLM(family="binomial", lambda_search=True, nlambdas=8,
            alpha=0.5, seed=7).train(y="y", training_frame=tr)
    assert m.output["reg_path"]["explained_deviance_valid"] is None
    assert m.output["lambda_best"] in m.output["reg_path"]["lambdas"]
    assert m.output["null_deviance"] > m.output["residual_deviance"]


def test_glm_lambda_min_ratio_honored(cl, rng):
    from h2o_tpu.models.glm import GLM
    tr = _sparse_binomial(rng, n=1000)
    m = GLM(family="binomial", lambda_search=True, nlambdas=5,
            lambda_min_ratio=0.1, alpha=1.0, seed=7).train(
        y="y", training_frame=tr)
    out = m.output
    assert out["lambda_min"] == pytest.approx(0.1 * out["lambda_max"])


def test_autoencoder_trains_and_scores_anomalies(cl, rng):
    from h2o_tpu.models.deeplearning import DeepLearning
    n, p = 2000, 8
    X = rng.normal(size=(n, p)).astype(np.float32)
    # inliers live on a 2D manifold; columns are correlated
    X[:, 2:] = X[:, :2] @ rng.normal(size=(2, p - 2)).astype(np.float32) \
        + 0.05 * X[:, 2:]
    fr = Frame([f"x{j}" for j in range(p)],
               [Vec(X[:, j]) for j in range(p)])
    ae = DeepLearning(autoencoder=True, hidden=[4], epochs=40,
                      seed=3).train(training_frame=fr)
    assert ae.output["autoencoder"] is True
    assert ae.output["model_category"] == "AutoEncoder"
    assert "MSE" in ae.output["training_metrics"].data

    # outliers off the manifold reconstruct worse
    Xo = rng.normal(size=(200, p)).astype(np.float32) * 3.0
    fro = Frame([f"x{j}" for j in range(p)],
                [Vec(Xo[:, j]) for j in range(p)])
    mse_in = ae.anomaly(fr)
    mse_out = ae.anomaly(fro)
    mi = float(np.nanmean(np.asarray(mse_in.vecs[0].to_numpy())))
    mo = float(np.nanmean(np.asarray(mse_out.vecs[0].to_numpy())))
    assert mse_in.names == ["Reconstruction.MSE"]
    assert mo > mi * 1.5

    # per-feature errors: one column per expanded input
    pf = ae.anomaly(fr, per_feature=True)
    assert len(pf.names) == p
    assert all(nm.startswith("reconstr_") and nm.endswith(".SE")
               for nm in pf.names)

    # reconstruction predict surface
    rec = ae.predict(fr)
    assert len(rec.names) == p
    assert all(nm.startswith("reconstr_") for nm in rec.names)


def test_autoencoder_no_response_required(cl, rng):
    from h2o_tpu.models.deeplearning import DeepLearning
    X = rng.normal(size=(200, 4)).astype(np.float32)
    fr = Frame([f"x{j}" for j in range(4)],
               [Vec(X[:, j]) for j in range(4)])
    b = DeepLearning(autoencoder=True, hidden=[2], epochs=2, seed=1)
    assert b.supervised is False
    m = b.train(training_frame=fr)
    assert m.output["weights"][0]["W"].shape[0] == 4
    assert m.output["weights"][-1]["W"].shape[1] == 4
