"""POST /3/Shutdown actually stops the serving surface (the round-2
verdict's 'lying no-op' item): jobs cancelled, store cleared, server down.
"""

import json
import time
import urllib.error
import urllib.request

import pytest


def test_shutdown_stops_server(cl):
    from h2o_tpu.api.server import RestServer
    from h2o_tpu.core.cloud import cloud

    srv = RestServer(port=0).start()
    base = f"http://127.0.0.1:{srv.port}"
    cloud().dkv.put("shutdown_probe", "x")

    with urllib.request.urlopen(f"{base}/3/Cloud", timeout=5) as r:
        assert json.loads(r.read())["cloud_healthy"]

    req = urllib.request.Request(f"{base}/3/Shutdown", data=b"",
                                 method="POST")
    with urllib.request.urlopen(req, timeout=5) as r:
        assert r.status == 200

    assert cloud().dkv.get("shutdown_probe") is None
    deadline = time.time() + 10
    down = False
    while time.time() < deadline:
        try:
            urllib.request.urlopen(f"{base}/3/Cloud", timeout=2)
        except (urllib.error.URLError, ConnectionError, OSError):
            down = True
            break
        time.sleep(0.3)
    assert down, "server still answering after /3/Shutdown"
    assert RestServer.current is None
