#!/usr/bin/env python
"""Chaos soak orchestrator — randomized multi-fault endurance runs.

In the discipline of Basiri et al. ("Chaos Engineering", IEEE Software
2016), a resilience mechanism is only real once the SYSTEM's invariants
are asserted under randomized, composed faults over real workloads —
not one injector at a time.  This driver composes the full injector set
(job faults, persist faults, stalls, slow scores, device OOMs, slice
losses, serve pressure) over a seeded workload mix (frame build +
rollups -> Rapids munge -> GBM train with resume -> grid -> online
serving through a 2-replica fleet) and asserts, after the clock runs
out:

- every job reached a terminal state (none wedged RUNNING);
- no leaked pool slots: both job pools return to their configured
  concurrency once wedged bodies drain;
- no leaked DKV keys: the store returns to its pre-soak key set;
- REST stayed responsive THROUGHOUT (every poll of /3/Resilience during
  the run answered inside its deadline);
- models recovered through faults are BITWISE-identical to a fault-free
  run of the same seed;
- every injected fault is accounted for: the chaos grand total equals
  the sum of the per-type counters, and OOM ladder events reconcile
  with the OOM injector's count.

Usage:
    python tools/soak.py --seed 7 --duration 60

Exit code 0 iff every invariant held; the report prints as JSON.
``tests/test_chaos_soak.py`` (pytest markers: soak + slow, excluded
from the tier-1 fast run) drives the same entry point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# allow `python tools/soak.py` from a source checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# INTERRUPTED is terminal for the job object: the work moved to a
# resumed job, it did not hang (core/job.py)
TERMINAL = ("DONE", "CANCELLED", "FAILED", "INTERRUPTED")

# fault mix: probabilities are deliberately moderate — the point is
# composition under load, not a 100% storm that never completes work.
# slice_loss_p fires at the tree-block dispatch and the membership
# probe: a hit interrupts the build resumably (checkpoints intact) and
# the soak's train_with_recovery retry path resumes it.
FAULTS = dict(job_p=0.15, persist_p=0.15, stall_p=0.10, stall_secs=1.0,
              score_slow_p=0.3, score_slow_ms=50.0, oom_p=0.10,
              slice_loss_p=0.05, serve_pressure_p=0.10)

# the serve leg's legal outcomes: protection statuses are contracts,
# crashes are not.  QueueFull/ShedLoad -> 429, TimeoutError -> 408,
# OOMError/BreakerOpen/MeshReforming/NoHealthyReplica -> 503 — all
# retryable; anything else is a serve_contract failure.
SERVE_RETRYABLE = ("QueueFull", "ShedLoad", "TimeoutError", "OOMError",
                   "BreakerOpen", "MeshReforming", "NoHealthyReplica",
                   "AdmissionRejected")


def _poll_rest(port: int, timeout: float = 5.0) -> dict:
    import urllib.request
    t0 = time.monotonic()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/3/Resilience",
            timeout=timeout) as r:
        payload = json.loads(r.read().decode())
    return {"latency": time.monotonic() - t0, "payload": payload}


def _train_reference(frame_of, seed: int):
    """Fault-free GBM of the soak's fixed (data, params) — the bitwise
    baseline every recovered model must reproduce."""
    from h2o_tpu.models.tree.gbm import GBM
    import numpy as np
    m = GBM(ntrees=4, max_depth=3, seed=seed,
            score_tree_interval=2).train(y="y", training_frame=frame_of())
    return np.asarray(m.predict_raw(frame_of()))


def _train_with_recovery(frame_of, seed: int, rec_dir: str,
                         max_tries: int = 8):
    """Train the same GBM under faults: injected job faults may kill the
    build; resume it from its recovery snapshot (or restart) until it
    completes.  Device OOMs are absorbed by the ladder underneath."""
    import numpy as np
    from h2o_tpu.core.recovery import auto_recover, pending_recoveries
    from h2o_tpu.models.tree.gbm import GBM
    for attempt in range(max_tries):
        try:
            if attempt > 0 and pending_recoveries(rec_dir):
                models = auto_recover(rec_dir)
                if models:
                    m = models[0]
                    return np.asarray(m.predict_raw(frame_of()))
                continue
            m = GBM(ntrees=4, max_depth=3, seed=seed,
                    score_tree_interval=2, recovery_dir=rec_dir,
                    checkpoint_interval=2,
                    model_id=f"soak_gbm_{seed}_{attempt}").train(
                        y="y", training_frame=frame_of())
            return np.asarray(m.predict_raw(frame_of()))
        except Exception:  # noqa: BLE001 — injected fault; try resume
            continue
    raise RuntimeError(f"GBM did not complete within {max_tries} "
                       f"attempts under fault injection")


def run_soak(seed: int = 7, duration: float = 60.0,
             faults: dict = None, verbose: bool = False) -> dict:
    """Run the soak; returns the invariant report (report['ok'] is the
    verdict).  Chaos state is reset on exit."""
    import numpy as np

    from h2o_tpu.api.server import RestServer
    from h2o_tpu.core import chaos, oom, resilience
    from h2o_tpu.core.cloud import Cloud
    from h2o_tpu.core.frame import Frame, T_CAT, Vec
    from h2o_tpu.rapids.interp import rapids_exec

    cl = Cloud.boot()
    rng = np.random.default_rng(seed)
    report = {"seed": seed, "duration": duration, "rounds": 0,
              "rest_polls": 0, "rest_max_latency": 0.0,
              "failures": [], "invariants": {}}

    def fail(inv: str, msg: str) -> None:
        report["failures"].append(f"{inv}: {msg}")

    # ---- baselines (fault-free) -------------------------------------
    chaos.reset()
    oom.reset_stats()
    resilience.reset_stats()
    keys_before = set(map(str, cl.dkv.keys()))
    pool_workers = cl.jobs._pool._max_workers
    sys_workers = cl.jobs._sys_pool._max_workers

    x = rng.normal(size=400).astype(np.float32)
    g = rng.integers(0, 6, size=400).astype(np.float32)
    y = (x + rng.normal(size=400) * 0.3 > 0).astype(np.int32)

    def frame_of():
        return Frame(["x", "y"],
                     [Vec(x), Vec(y, T_CAT, domain=["n", "p"])])

    pred_ref = _train_reference(frame_of, seed)
    gb_ast = '(GB soak_fr [1] sum 0 "all" mean 0 "all" nrow 0 "all")'
    cl.dkv.put("soak_fr", Frame(["x", "g"], [Vec(x), Vec(g)]))
    gb_ref = [c.to_numpy().copy() for c in rapids_exec(gb_ast).vecs]

    srv = RestServer(port=0).start()
    rec_root = os.path.join(cl.args.ice_root, f"soak_rec_{seed}")

    # ---- the storm --------------------------------------------------
    f = dict(FAULTS, **(faults or {}))
    chaos.configure(seed=seed, **f)
    t_end = time.monotonic() + duration
    deployed = []
    try:
        while time.monotonic() < t_end:
            r = report["rounds"]
            report["rounds"] += 1
            # REST must answer while the storm runs
            try:
                p = _poll_rest(srv.port)
                report["rest_polls"] += 1
                report["rest_max_latency"] = max(
                    report["rest_max_latency"], p["latency"])
            except Exception as e:  # noqa: BLE001
                fail("rest_responsive", repr(e))
            # 1. frame build + rollups (device_put / map_reduce surface)
            try:
                fr = Frame(["a"], [Vec(rng.normal(size=256)
                                       .astype(np.float32))])
                fr.vec("a").mean()
            except Exception:  # noqa: BLE001 — injected faults are fine
                pass
            # 2. munge: the group-by must ALWAYS reproduce the baseline
            #    — bitwise while on device (sweep/shrink rungs), to
            #    float noise if the ladder lands on the host oracle
            #    (different summation order, same parity contract)
            try:
                fb_before = oom.stats()["sites"].get(
                    "munge.groupby", {}).get("host_fallbacks", 0)
                out = rapids_exec(gb_ast)
                fb_after = oom.stats()["sites"].get(
                    "munge.groupby", {}).get("host_fallbacks", 0)
                exact = fb_after == fb_before
                for a, b in zip(gb_ref, out.vecs):
                    got = b.to_numpy()
                    ok = np.array_equal(a, got) if exact else \
                        np.allclose(a, got, rtol=1e-5, atol=1e-6)
                    if not ok:
                        fail("groupby_bitwise", f"round {r} diverged")
                        break
            except Exception as e:  # noqa: BLE001
                fail("groupby_completes", f"round {r}: {e!r}")
            # 3. train with resume; bitwise against the fault-free model
            try:
                pred = _train_with_recovery(
                    frame_of, seed, os.path.join(rec_root, f"r{r}"))
                if not np.array_equal(pred_ref, pred):
                    fail("model_bitwise", f"round {r} diverged")
            except Exception as e:  # noqa: BLE001
                fail("train_completes", f"round {r}: {e!r}")
            # 4. grid: failures are collected, never wedge the pool
            try:
                from h2o_tpu.models.grid import GridSearch
                from h2o_tpu.models.tree.gbm import GBM
                gs = GridSearch(GBM, {"ntrees": [2, 3]}, max_depth=2,
                                seed=seed, grid_id=f"soak_grid_{r}")
                grid = gs.train(y="y", training_frame=frame_of())
                if len(grid.models) + len(grid.failures) != 2:
                    fail("grid_accounting",
                         f"round {r}: {len(grid.models)} models + "
                         f"{len(grid.failures)} failures != 2")
            except Exception:  # noqa: BLE001 — whole-grid injected kill
                pass
            # 5. serve: deploy across the replica fleet, score through
            #    the fleet router (slow-score shedding and breaker
            #    trips are legal: 429/408/503 are contracts, crashes
            #    are not), undeploy.  Injected serve pressure
            #    (serve_pressure_p) drives the breaker through its full
            #    protocol while the rest of the storm runs.
            try:
                from h2o_tpu.serve import ServingConfig
                from h2o_tpu.serve.replica import fleet
                from h2o_tpu.models.tree.gbm import GBM
                fl = fleet(2)         # multi-replica serve contract
                m = None
                for _ in range(6):    # injected job faults may kill it
                    try:
                        m = GBM(ntrees=2, max_depth=2, seed=seed).train(
                            y="y", training_frame=frame_of())
                        break
                    except Exception:  # noqa: BLE001 — retry the build
                        continue
                if m is None:
                    continue          # storm won this round; next one
                name = f"soak_dep_{r}"
                fl.deploy(name, m, ServingConfig(), warm=False)
                deployed.append(name)
                rows = [{"x": float(v)} for v in x[:4]]
                for _ in range(4):
                    try:
                        fl.score_rows(name, rows, deadline_ms=2000)
                    except Exception as e:  # noqa: BLE001
                        if type(e).__name__ not in SERVE_RETRYABLE:
                            fail("serve_contract",
                                 f"round {r}: unexpected {e!r}")
                fl.undeploy(name, drain_secs=2.0)
                deployed.remove(name)
            except Exception as e:  # noqa: BLE001
                fail("serve_lifecycle", f"round {r}: {e!r}")
            if verbose:
                print(f"[soak] round {r} done, "
                      f"{t_end - time.monotonic():.0f}s left",
                      file=sys.stderr)
    finally:
        chaos_counters = chaos.chaos().counters()
        oom_stats = oom.stats()
        from h2o_tpu.serve.registry import serving_stats
        serve_stats = serving_stats()
        chaos.reset()                 # faults OFF before teardown
        from h2o_tpu.serve.replica import fleet as _fleet, reset_fleet
        for name in deployed:
            try:
                _fleet().undeploy(name, drain_secs=0.5)
            except Exception:  # noqa: BLE001
                pass
        reset_fleet()
        srv.stop()

    # ---- invariants -------------------------------------------------
    inv = report["invariants"]
    # jobs: give stalled bodies (stall_secs) time to reach terminal
    deadline = time.monotonic() + 4 * f["stall_secs"] + 10.0
    while time.monotonic() < deadline:
        live = [j for j in cl.jobs.list() if j.status not in TERMINAL]
        if not live:
            break
        time.sleep(0.2)
    live = [f"{j.key}:{j.status}" for j in cl.jobs.list()
            if j.status not in TERMINAL]
    inv["jobs_terminal"] = not live
    if live:
        fail("jobs_terminal", f"non-terminal jobs: {live[:5]}")
    # pool slots: compensation slots must have been given back
    pw, sw = cl.jobs._pool._max_workers, cl.jobs._sys_pool._max_workers
    inv["pool_slots"] = (pw == pool_workers and sw == sys_workers)
    if not inv["pool_slots"]:
        fail("pool_slots", f"user {pool_workers}->{pw}, "
                           f"system {sys_workers}->{sw}")
    # DKV: purge soak keys, then demand the pre-soak key set
    for k in list(map(str, cl.dkv.keys())):
        if k not in keys_before:
            cl.dkv.remove(k, force=True)
    leaked = set(map(str, cl.dkv.keys())) ^ keys_before
    inv["dkv_clean"] = not leaked
    if leaked:
        fail("dkv_clean", f"key-set drift: {sorted(leaked)[:10]}")
    # REST responded at least once a round
    inv["rest_responsive"] = report["rest_polls"] >= report["rounds"]
    if not inv["rest_responsive"]:
        fail("rest_responsive",
             f"{report['rest_polls']} polls < {report['rounds']} rounds")
    # fault accounting: grand total == sum of per-type counters, and
    # ladder OOM events reconcile with the OOM injector's count
    per_type = {k: v for k, v in chaos_counters.items()
                if k != "injected"}
    inv["faults_accounted"] = (
        chaos_counters["injected"] == sum(per_type.values()))
    if not inv["faults_accounted"]:
        fail("faults_accounted",
             f"injected={chaos_counters['injected']} != "
             f"sum({per_type})")
    inv["oom_ladder_accounted"] = (
        oom_stats["oom_events"] >= chaos_counters["injected_oom"])
    if not inv["oom_ladder_accounted"]:
        fail("oom_ladder_accounted",
             f"ladder saw {oom_stats['oom_events']} OOMs < injector's "
             f"{chaos_counters['injected_oom']}")
    report["chaos"] = chaos_counters
    report["oom"] = oom_stats
    report["retry"] = resilience.stats()
    report["serving"] = serve_stats
    report["ok"] = not report["failures"]
    return report


def _append_rows(path: str, rng, n: int) -> None:
    """Append ``n`` CSV rows to a follow-mode source (one flush, like a
    producer's atomic append)."""
    import numpy as np
    xs = rng.normal(size=n).astype(np.float32)
    ys = np.where(xs > 0, "p", "n")
    with open(path, "a") as fobj:
        for v, lab in zip(xs, ys):
            fobj.write(f"{v:.6f},{lab}\n")


def _follow_kill_resume_check(cl, seed: int, rec_root: str,
                              fail) -> dict:
    """The exactly-once cursor drill: a follow pipeline is KILLED
    mid-stream (cancel), resumed from its durable per-source byte
    cursor, and the landed frame must be BITWISE what an uninterrupted
    replay of the same final file lands — no duplicated rows, no
    dropped rows.  emit_partial=False pins chunk boundaries to record
    boundaries so the comparison is content-only."""
    import numpy as np
    from h2o_tpu.stream import ChunkReader, start_pipeline, stop_pipeline

    rng = np.random.default_rng(seed + 100)
    path = os.path.join(rec_root, f"mt_follow_{seed}.csv")
    os.makedirs(rec_root, exist_ok=True)
    with open(path, "w") as fobj:
        fobj.write("x,y\n")
    _append_rows(path, rng, 200)

    def mk_reader():
        return ChunkReader(path, chunk_rows=64, follow=True,
                           poll_ms=20, emit_partial=False)

    common = dict(algo="gbm",
                  model_params=dict(max_depth=2, seed=seed, nbins=16,
                                    ntrees=0),
                  refresh_chunks=10 ** 6, trees_per_refresh=2,
                  recovery_dir=rec_root, dest_frame="mt_follow_frame")
    pipe = start_pipeline("mt_follow", mk_reader(), "y", **common)
    deadline = time.monotonic() + 30
    while pipe.chunks_landed < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    pipe.stop()                               # KILL mid-stream
    try:
        pipe.job.join(timeout=30)
    except Exception:  # noqa: BLE001 — cancellation IS the drill
        pass
    killed_at = pipe.chunks_landed
    # producer keeps writing while the pipeline is down
    for _ in range(6):
        _append_rows(path, rng, 100)
    # resume: a NEW pipeline restores the byte cursor and re-attaches
    pipe2 = start_pipeline("mt_follow", mk_reader(), "y",
                           resume=True, **common)
    # wait for the live follow to catch up to within one chunk of the
    # appended tail (emit_partial=False only lands FULL chunks while
    # the source is live; finish() drains the sub-chunk remainder)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if pipe2.status()["rows_landed"] >= 700:
            break
        time.sleep(0.05)
    pipe2.finish()                            # graceful drain -> DONE
    pipe2.job.join(timeout=60)
    # uninterrupted replay of the SAME final file
    replay = start_pipeline(
        "mt_follow_replay",
        ChunkReader(path, chunk_rows=64, emit_partial=False), "y",
        algo="gbm",
        model_params=dict(max_depth=2, seed=seed, nbins=16, ntrees=0),
        refresh_chunks=10 ** 6, trees_per_refresh=2,
        dest_frame="mt_follow_replay_frame")
    replay.job.join(timeout=120)
    fa = cl.dkv.get("mt_follow_frame")
    fb = cl.dkv.get("mt_follow_replay_frame")
    ok = fa is not None and fb is not None and fa.nrows == fb.nrows
    if ok:
        for col in ("x", "y"):
            a = fa.vec(col).to_numpy()[:fa.nrows]
            b = fb.vec(col).to_numpy()[:fb.nrows]
            if not np.array_equal(a, b):
                ok = False
                break
    if not ok:
        fail("follow_no_dup_drop",
             f"resumed frame != uninterrupted replay "
             f"(rows {getattr(fa, 'nrows', None)} vs "
             f"{getattr(fb, 'nrows', None)})")
    out = {"killed_after_chunks": killed_at,
           "resumed_rows": getattr(fa, "nrows", 0),
           "replay_rows": getattr(fb, "nrows", 0), "bitwise": ok}
    stop_pipeline("mt_follow", remove=True)
    stop_pipeline("mt_follow_replay", remove=True)
    return out


def run_multitenant_soak(seed: int = 7, duration: float = 60.0,
                         verbose: bool = False) -> dict:
    """The isolation soak (PR 20): three weighted tenants each run
    AutoML + a follow-mode streaming refresh + serve traffic while the
    chaos layer injects admission rejections, serve pressure, and a
    GUARANTEED mid-soak slice loss.  Asserts, after the clock runs out:

    - every job (AutoML parents, stream pipelines, singles) reached a
      terminal state;
    - ZERO cross-tenant evictions below the high-water mark — tenant
      A's pressure never evicted tenant B's resident blocks while the
      pool had headroom;
    - every admission refusal is CLASSIFIED (rejected total == the sum
      over AdmissionRejected.REASONS buckets; injected rejects
      reconcile with the chaos counter);
    - per-tenant serve p99 stays under the bound and every tenant got
      successful scores THROUGH the storm;
    - models trained under the storm are BITWISE identical to the
      fault-free baseline of the same seed (slice-loss recovery
      included);
    - a follow pipeline killed mid-stream resumes from its durable
      cursor with no duplicated and no dropped rows (bitwise vs an
      uninterrupted replay);
    - both job pools return to their configured width.
    """
    import threading
    import numpy as np

    from h2o_tpu.api.server import RestServer
    from h2o_tpu.core import chaos, oom, resilience
    from h2o_tpu.core.cloud import Cloud
    from h2o_tpu.core.frame import Frame, T_CAT, Vec
    from h2o_tpu.core.memory import manager
    from h2o_tpu.core.tenant import (AdmissionRejected, create_tenant,
                                     delete_tenant, tenant_context)
    from h2o_tpu.models.tree.gbm import GBM
    from h2o_tpu.serve import ServingConfig
    from h2o_tpu.serve.registry import registry
    from h2o_tpu.stream import ChunkReader, start_pipeline, stop_pipeline

    cl = Cloud.boot()
    report = {"seed": seed, "duration": duration, "rounds": 0,
              "rest_polls": 0, "rest_max_latency": 0.0,
              "stream_restarts": 0, "failures": [], "invariants": {}}
    p99_bound_ms = float(os.environ.get("MT_SOAK_P99_MS", 2000.0))

    def fail(inv: str, msg: str) -> None:
        report["failures"].append(f"{inv}: {msg}")

    chaos.reset()
    oom.reset_stats()
    resilience.reset_stats()
    keys_before = set(map(str, cl.dkv.keys()))
    pool_workers = cl.jobs._pool._max_workers
    sys_workers = cl.jobs._sys_pool._max_workers
    rec_root = os.path.join(cl.args.ice_root, f"mt_soak_{seed}")
    os.makedirs(rec_root, exist_ok=True)

    tenants = {"acme": 3.0, "globex": 2.0, "initech": 1.0}
    for name, w in tenants.items():
        create_tenant(name, weight=w, hbm_share=0.25, max_queue=4)

    # per-tenant fixed datasets + fault-free bitwise baselines
    tdata = {}
    for i, name in enumerate(tenants):
        trng = np.random.default_rng(seed + i)
        tx = trng.normal(size=400).astype(np.float32)
        ty = (tx + trng.normal(size=400) * 0.3 > 0).astype(np.int32)
        tdata[name] = (tx, ty)

    def frame_of(name):
        tx, ty = tdata[name]
        return Frame(["x", "y"],
                     [Vec(tx), Vec(ty, T_CAT, domain=["n", "p"])])

    pred_ref = {name: _train_reference(lambda n=name: frame_of(n), seed)
                for name in tenants}

    # ---- phase 0 (chaos OFF): follow kill/resume exactly-once -------
    report["follow_drill"] = _follow_kill_resume_check(
        cl, seed, rec_root, fail)

    # serve alias for the storm's per-tenant traffic
    m0 = GBM(ntrees=2, max_depth=2, seed=seed).train(
        y="y", training_frame=frame_of("acme"))
    alias = "mt_serve"
    registry().deploy(alias, m0, ServingConfig(queue_cap=128))

    srv = RestServer(port=0).start()
    storm = dict(admission_reject_p=0.10, serve_pressure_p=0.10,
                 slice_loss_p=0.02)
    chaos.configure(seed=seed, **storm)
    counters_accum = {}

    def _accumulate(c):
        for k, v in c.items():
            counters_accum[k] = counters_accum.get(k, 0) + v

    # per-tenant follow streams: a feeder appends rows, the pipeline
    # refreshes on cadence; a pipeline interrupted by the slice-loss
    # reform is RESTARTED with resume=True (the cursor re-attach path)
    streams, feeders_stop = {}, threading.Event()

    def stream_common(name):
        return dict(algo="gbm",
                    model_params=dict(max_depth=2, seed=seed, nbins=16,
                                      ntrees=0),
                    refresh_chunks=3, trees_per_refresh=2,
                    recovery_dir=rec_root,
                    dest_frame=f"mt_stream_{name}_frame")

    def start_stream(name, resume=False):
        path = os.path.join(rec_root, f"mt_stream_{name}.csv")
        if not resume:
            with open(path, "w") as fobj:
                fobj.write("x,y\n")
            _append_rows(path, np.random.default_rng(seed), 120)
        with tenant_context(name):
            return start_pipeline(
                f"mt_stream_{name}",
                ChunkReader(path, chunk_rows=60, follow=True,
                            poll_ms=50, emit_partial=False),
                "y", resume=resume, **stream_common(name))

    def feeder():
        frng = np.random.default_rng(seed + 50)
        while not feeders_stop.is_set():
            for name in tenants:
                _append_rows(os.path.join(
                    rec_root, f"mt_stream_{name}.csv"), frng, 60)
            feeders_stop.wait(1.0)

    for name in tenants:
        for _ in range(12):    # chaos may refuse the pipeline job
            try:
                streams[name] = start_stream(name)
                break
            except AdmissionRejected as e:
                if e.reason not in AdmissionRejected.REASONS:
                    fail("refusals_classified",
                         f"unclassified stream reject: {e.reason}")
                time.sleep(0.05)
        else:
            fail("stream_resume",
                 f"tenant {name}: stream launch refused 12 times")
    feeder_t = threading.Thread(target=feeder, daemon=True)
    feeder_t.start()

    # one AutoML per tenant — ONE logical admission each; inner builds
    # ride the parent's slot
    aml_jobs = {}
    for name in tenants:
        from h2o_tpu.automl.automl import AutoML
        with tenant_context(name):
            # chaos may refuse the launch itself — that's a classified
            # refusal (the FAILED job stays on the books), so retry
            # under a fresh project until one admission sticks
            for attempt in range(12):
                try:
                    aml_jobs[name] = AutoML(
                        max_models=2, nfolds=2, seed=seed,
                        include_algos=["GBM", "GLM"],
                        project_name=f"mt_aml_{name}_{attempt}",
                        ).train_async(
                        y="y", training_frame=frame_of(name))
                    break
                except AdmissionRejected as e:
                    if e.reason not in AdmissionRejected.REASONS:
                        fail("refusals_classified",
                             f"unclassified launch reject: {e.reason}")
                    time.sleep(0.05)
            else:
                fail("jobs_terminal",
                     f"AutoML launch for {name} refused 12 times")

    # per-tenant serve hammers
    lat = {name: [] for name in tenants}
    lat_lock = threading.Lock()
    hammer_stop = threading.Event()

    def hammer(name):
        probe = [{"x": 0.1}]
        while not hammer_stop.is_set():
            h0 = time.monotonic()
            try:
                registry().score_rows(alias, probe, deadline_ms=2000,
                                      tenant=name)
                with lat_lock:
                    lat[name].append((time.monotonic() - h0) * 1000.0)
            except Exception as e:  # noqa: BLE001 — contract statuses
                if type(e).__name__ not in SERVE_RETRYABLE and \
                        not isinstance(e, KeyError):
                    fail("serve_contract", f"tenant {name}: "
                                           f"unexpected {e!r}")
            time.sleep(0.01)

    hammers = [threading.Thread(target=hammer, args=(n,), daemon=True)
               for n in tenants]
    for h in hammers:
        h.start()

    burst_jobs, burst_rejects = [], 0
    t_end = time.monotonic() + duration
    drill_fired = False
    try:
        # run until the clock runs out AND the slice-loss drill has had
        # one full round to fire — short durations must not skip it
        while time.monotonic() < t_end or not drill_fired:
            r = report["rounds"]
            report["rounds"] += 1
            try:
                p = _poll_rest(srv.port)
                report["rest_polls"] += 1
                report["rest_max_latency"] = max(
                    report["rest_max_latency"], p["latency"])
            except Exception as e:  # noqa: BLE001
                fail("rest_responsive", repr(e))
            # guaranteed slice loss once past half time: the at-block
            # drill fires deterministically at the next tree dispatch
            if not drill_fired and \
                    time.monotonic() > t_end - duration / 2:
                _accumulate(chaos.chaos().counters())
                chaos.configure(seed=seed + 1, slice_loss_at_block=2,
                                **{k: v for k, v in storm.items()
                                   if k != "slice_loss_p"})
                drill_fired = True
            # bitwise train per tenant under the storm (admission
            # rejects and slice-loss interrupts retried inside)
            for name in tenants:
                try:
                    with tenant_context(name):
                        pred = _train_with_recovery(
                            lambda n=name: frame_of(n), seed,
                            os.path.join(rec_root, f"{name}_r{r}"))
                    if not np.array_equal(pred_ref[name], pred):
                        fail("model_bitwise",
                             f"tenant {name} round {r} diverged")
                except Exception as e:  # noqa: BLE001
                    fail("train_completes",
                         f"tenant {name} round {r}: {e!r}")
            # queue-bound burst: initech floods its bounded queue; the
            # overflow MUST come back as classified queue_full rejects
            if r == 0:
                with tenant_context("initech"):
                    for i in range(8):
                        try:
                            burst_jobs.append(
                                GBM(ntrees=1, max_depth=2,
                                    seed=seed + i).train_async(
                                    y="y",
                                    training_frame=frame_of("initech")))
                        except AdmissionRejected as e:
                            burst_rejects += 1
                            if e.reason not in AdmissionRejected.REASONS:
                                fail("refusals_classified",
                                     f"unclassified reason {e.reason}")
            # restart any stream pipeline the reform interrupted —
            # the durable cursor makes the re-attach exactly-once
            for name, pipe in list(streams.items()):
                if pipe.job is not None and \
                        pipe.job.status in TERMINAL:
                    try:
                        streams[name] = start_stream(name, resume=True)
                        report["stream_restarts"] += 1
                    except AdmissionRejected as e:
                        # classified refusal — retry next round
                        if e.reason not in AdmissionRejected.REASONS:
                            fail("refusals_classified",
                                 f"unclassified stream reject: "
                                 f"{e.reason}")
                    except Exception as e:  # noqa: BLE001
                        fail("stream_resume",
                             f"tenant {name}: {e!r}")
            if verbose:
                print(f"[mt-soak] round {r} done, "
                      f"{t_end - time.monotonic():.0f}s left",
                      file=sys.stderr)
    finally:
        _accumulate(chaos.chaos().counters())
        oom_stats = oom.stats()
        chaos.reset()                 # faults OFF before teardown
        hammer_stop.set()
        feeders_stop.set()
        feeder_t.join(timeout=5)
        for h in hammers:
            h.join(timeout=5)
        for name, pipe in streams.items():
            try:
                pipe.finish()
                if pipe.job is not None:
                    pipe.job.join(timeout=120)
            except Exception:  # noqa: BLE001
                pass
            stop_pipeline(f"mt_stream_{name}", remove=True)
        stop_pipeline("mt_follow", remove=True)
        stop_pipeline("mt_follow_replay", remove=True)
        for name, job in aml_jobs.items():
            try:
                job.join(timeout=300)
            except Exception:  # noqa: BLE001 — terminal-state check below
                pass
        for j in burst_jobs:
            try:
                j.join(timeout=120)
            except Exception:  # noqa: BLE001
                pass
        try:
            registry().undeploy(alias, drain_secs=1.0)
        except KeyError:
            pass
        srv.stop()

    # ---- invariants -------------------------------------------------
    inv = report["invariants"]
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        live = [j for j in cl.jobs.list() if j.status not in TERMINAL]
        if not live:
            break
        time.sleep(0.2)
    live = [f"{j.key}:{j.status}" for j in cl.jobs.list()
            if j.status not in TERMINAL]
    inv["jobs_terminal"] = not live
    if live:
        fail("jobs_terminal", f"non-terminal jobs: {live[:5]}")

    adm = cl.jobs.admission.stats()
    report["admission"] = adm
    classified = sum(adm["rejects_by_reason"].values())
    unknown = set(adm["rejects_by_reason"]) - set(
        AdmissionRejected.REASONS)
    inv["refusals_classified"] = (
        adm["rejected"] == classified and not unknown)
    if not inv["refusals_classified"]:
        fail("refusals_classified",
             f"rejected={adm['rejected']} classified={classified} "
             f"unknown_reasons={sorted(unknown)}")
    injected_rejects = counters_accum.get("injected_admission_rejects",
                                          0)
    inv["injected_rejects_accounted"] = (
        adm["rejects_by_reason"].get("injected", 0) == injected_rejects)
    if not inv["injected_rejects_accounted"]:
        fail("injected_rejects_accounted",
             f"admission saw "
             f"{adm['rejects_by_reason'].get('injected', 0)} != "
             f"chaos {injected_rejects}")
    report["burst_rejects"] = burst_rejects

    mem = manager().stats()
    inv["tenant_isolation"] = mem["cross_tenant_below_highwater"] == 0
    if not inv["tenant_isolation"]:
        fail("tenant_isolation",
             f"{mem['cross_tenant_below_highwater']} cross-tenant "
             f"evictions below the high-water mark")
    report["memory_tenants"] = mem.get("tenants")
    report["cross_tenant_evictions"] = mem["cross_tenant_evictions"]

    inv["slice_loss_fired"] = \
        counters_accum.get("injected_slice_losses", 0) >= 1
    if not inv["slice_loss_fired"]:
        fail("slice_loss_fired", "no slice loss fired mid-soak")

    p99s = {}
    for name, vals in lat.items():
        if not vals:
            fail("serve_per_tenant",
                 f"tenant {name} got zero successful scores")
            continue
        p99s[name] = float(np.percentile(vals, 99))
        if p99s[name] > p99_bound_ms:
            fail("serve_per_tenant",
                 f"tenant {name} p99 {p99s[name]:.0f}ms > bound "
                 f"{p99_bound_ms:.0f}ms")
    inv["serve_per_tenant"] = not any(
        f.startswith("serve_per_tenant") for f in report["failures"])
    report["serve_p99_ms"] = {k: round(v, 2) for k, v in p99s.items()}

    pw, sw = cl.jobs._pool._max_workers, cl.jobs._sys_pool._max_workers
    inv["pool_slots"] = (pw == pool_workers and sw == sys_workers)
    if not inv["pool_slots"]:
        fail("pool_slots", f"user {pool_workers}->{pw}, "
                           f"system {sys_workers}->{sw}")

    for name in tenants:
        delete_tenant(name)
    for k in list(map(str, cl.dkv.keys())):
        if k not in keys_before:
            cl.dkv.remove(k, force=True)
    leaked = set(map(str, cl.dkv.keys())) ^ keys_before
    inv["dkv_clean"] = not leaked
    if leaked:
        fail("dkv_clean", f"key-set drift: {sorted(leaked)[:10]}")

    report["chaos"] = counters_accum
    report["oom"] = oom_stats
    report["retry"] = resilience.stats()
    report["ok"] = not report["failures"]
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--duration", type=float, default=60.0,
                    help="soak wall-clock seconds (default 60)")
    ap.add_argument("--multitenant", action="store_true",
                    help="run the multi-tenant isolation soak instead "
                         "of the single-tenant chaos storm")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    runner = run_multitenant_soak if args.multitenant else run_soak
    report = runner(seed=args.seed, duration=args.duration,
                    verbose=args.verbose)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
