"""Resilience layer: retry/backoff, deadlines, job watchdog, bounded
registry, and recovery-snapshot robustness (core/resilience.py,
core/job.py, core/recovery.py).  Fast tier — no model builds; the
compile-heavy chaos soak lives in test_chaos.py."""

import json
import os
import threading
import time

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _reset():
    from h2o_tpu.core import chaos, resilience
    resilience.reset_stats()
    yield
    chaos.reset()
    resilience.reset_stats()


# -- RetryPolicy / Deadline --------------------------------------------------

def test_retry_recovers_transient():
    from h2o_tpu.core.resilience import RetryPolicy, stats
    pol = RetryPolicy(max_attempts=4, base_delay=0.001, max_delay=0.002)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionError("transient")
        return "ok"

    assert pol.call(flaky) == "ok"
    assert calls["n"] == 3
    st = stats()
    assert st["retries"] == 2 and st["recoveries"] == 1


def test_retry_permanent_raises_immediately():
    from h2o_tpu.core.resilience import RetryPolicy, stats
    pol = RetryPolicy(max_attempts=5, base_delay=0.001)
    calls = {"n": 0}

    def perm():
        calls["n"] += 1
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        pol.call(perm)
    assert calls["n"] == 1          # no pointless retries
    assert stats()["permanent_failures"] == 1


def test_retry_gives_up_after_max_attempts():
    from h2o_tpu.core.resilience import RetryPolicy, stats
    pol = RetryPolicy(max_attempts=3, base_delay=0.001, max_delay=0.001)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise ConnectionError("still down")

    with pytest.raises(ConnectionError):
        pol.call(always)
    assert calls["n"] == 3
    assert stats()["giveups"] == 1


def test_retry_http_classification():
    from h2o_tpu.core.resilience import is_retryable
    import urllib.error
    mk = lambda code: urllib.error.HTTPError("http://x", code, "m", {},
                                             None)
    assert is_retryable(mk(503)) and is_retryable(mk(429))
    assert not is_retryable(mk(404)) and not is_retryable(mk(403))
    assert is_retryable(ConnectionResetError("rst"))
    assert not is_retryable(ValueError("bad arg"))


def test_deadline():
    from h2o_tpu.core.resilience import Deadline
    d = Deadline(0.02)
    assert not d.expired and d.remaining() > 0
    time.sleep(0.03)
    assert d.expired
    with pytest.raises(TimeoutError):
        d.check("thing")
    assert Deadline(0).remaining() == float("inf")   # unbounded


def test_retry_respects_deadline():
    from h2o_tpu.core.resilience import Deadline, RetryPolicy
    pol = RetryPolicy(max_attempts=100, base_delay=0.05, max_delay=0.05)
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        pol.call(always, deadline=Deadline(0.1))
    assert calls["n"] < 10          # budget cut it off long before 100


# -- persist retry wiring ----------------------------------------------------

def test_persist_transient_faults_recovered(tmp_path):
    """The acceptance drill: byte-store ops succeed under fail-N-then-
    succeed injection, with injected and retry counts observable."""
    from h2o_tpu.core import chaos, persist, resilience
    chaos.configure(persist_transient=2, seed=0)
    uri = str(tmp_path / "blob.bin")
    persist.write_bytes(uri, b"payload")
    assert persist.read_bytes(uri) == b"payload"
    c = chaos.chaos()
    assert c.injected_persist == 4          # 2 per op, write + read
    assert resilience.stats()["retries"] >= 4
    assert resilience.stats()["recoveries"] == 2


def test_persist_permanent_still_raises(tmp_path, cl):
    from h2o_tpu.core import persist, resilience
    with pytest.raises(FileNotFoundError):
        persist.read_bytes(str(tmp_path / "nope.bin"))
    with pytest.raises(NotImplementedError):
        persist.read_bytes("s3://bucket/key")
    assert resilience.stats()["retries"] == 0


def test_frame_snapshot_under_transient_faults(cl, tmp_path):
    from h2o_tpu.core import chaos, persist
    from h2o_tpu.core.frame import Frame, Vec
    rng = np.random.default_rng(0)
    fr = Frame(["a", "b"], [Vec(rng.normal(size=40).astype(np.float32)),
                            Vec(rng.normal(size=40).astype(np.float32))])
    chaos.configure(persist_transient=1, seed=0)
    persist.save_frame(fr, str(tmp_path / "snap"))
    fr2 = persist.load_frame(str(tmp_path / "snap"))
    assert fr2.nrows == fr.nrows
    np.testing.assert_allclose(fr2.vec("a").to_numpy(),
                               fr.vec("a").to_numpy())
    assert chaos.chaos().injected_persist >= 4


# -- recovery snapshot robustness -------------------------------------------

def test_pending_recoveries_skips_corrupt_snapshot(tmp_path):
    from h2o_tpu.core.recovery import pending_recoveries
    rd = tmp_path / "rec"
    good = rd / "grid_ok"
    good.mkdir(parents=True)
    (good / "info.json").write_text(json.dumps(
        {"kind": "grid", "job_id": "ok", "done": False, "started": 1.0,
         "models": []}))
    bad = rd / "grid_bad"
    bad.mkdir()
    (bad / "info.json").write_text('{"kind": "grid", "job_')  # torn write
    worse = rd / "grid_worse"
    worse.mkdir()
    (worse / "info.json").write_text("[1, 2, 3]")             # wrong shape
    pend = pending_recoveries(str(rd))
    assert [p["job_id"] for p in pend] == ["ok"]


def test_iteration_checkpoint_roundtrip(tmp_path):
    from h2o_tpu.core.recovery import Recovery
    rec = Recovery(str(tmp_path), "model", "m1")
    rec.save_iteration({"kind": "tree", "done": 4,
                        "arr": np.arange(8, dtype=np.float32)},
                       meta={"kind": "tree", "trees_done": 4})
    st = rec.load_iteration()
    assert st["done"] == 4
    np.testing.assert_array_equal(st["arr"],
                                  np.arange(8, dtype=np.float32))
    assert rec.iteration_meta()["trees_done"] == 4
    # corrupt payload degrades to "no checkpoint", never a crash
    with open(os.path.join(rec.dir, "iter.pkl"), "wb") as f:
        f.write(b"\x80garbage")
    assert rec.load_iteration() is None
    rec.clear_iteration()
    assert rec.iteration_meta() is None


# -- Job.join semantics ------------------------------------------------------

def test_join_chains_original_traceback(cl):
    from h2o_tpu.core.job import Job

    def boom(j):
        raise ValueError("kapow")

    job = cl.jobs.start(Job(description="boom"), boom)
    with pytest.raises(ValueError, match="kapow") as ei:
        job.join(10)
    # the original exception (with its worker-thread traceback) is the
    # explicit cause; the registry keeps it un-mutated for /3/Jobs
    assert ei.value.__cause__ is job.exception
    assert ei.value.__cause__.__traceback__ is not None


def test_join_failed_without_exception_guard():
    from h2o_tpu.core.job import FAILED, Job
    j = Job(description="weird")
    j.status = FAILED
    j._done.set()
    with pytest.raises(RuntimeError, match="no recorded exception"):
        j.join(1)


# -- registry bound ----------------------------------------------------------

def test_terminal_jobs_lru_evicted():
    from h2o_tpu.core.job import Job, JobRegistry
    reg = JobRegistry(max_workers=2, jobs_cap=5)
    jobs = [reg.start(Job(description=f"q{i}"), lambda j: None)
            for i in range(10)]
    for j in jobs:
        j._done.wait(10)
    last = reg.start(Job(description="last"), lambda j: None)
    last.join(10)
    assert len(reg.list()) <= 5
    assert reg.evicted_count >= 5
    # the newest job survives eviction
    assert reg.get(str(last.key)) is not None


def test_running_jobs_never_evicted():
    from h2o_tpu.core.job import Job, JobRegistry
    reg = JobRegistry(max_workers=4, jobs_cap=2)
    gate = threading.Event()
    live = [reg.start(Job(description=f"live{i}"),
                      lambda j: gate.wait(20)) for i in range(3)]
    try:
        done = reg.start(Job(description="done"), lambda j: None)
        done.join(10)
        for j in live:          # over cap, but RUNNING jobs must remain
            assert reg.get(str(j.key)) is not None
    finally:
        gate.set()
        for j in live:
            j._done.wait(10)


# -- deadlines + watchdog ----------------------------------------------------

def test_stall_watchdog_expires_and_frees_slot():
    """A hung job (no heartbeat) is FAILED(TimeoutError) and its pool
    slot reclaimed: a subsequent job on the same 1-worker pool runs."""
    from h2o_tpu.core.job import FAILED, Job, JobRegistry
    reg = JobRegistry(max_workers=1, watchdog_interval=0.1)
    release = threading.Event()
    stuck = reg.start(Job(description="stuck", stall_secs=0.4),
                      lambda j: release.wait(20))
    try:
        with pytest.raises(TimeoutError, match="stall window"):
            stuck.join(10)
        assert stuck.status == FAILED
        assert isinstance(stuck.exception, TimeoutError)
        assert stuck.to_dict()["timed_out"] is True
        nxt = reg.start(Job(description="next"), lambda j: "ran")
        assert nxt.join(10) == "ran"
    finally:
        release.set()


def test_deadline_expires_cooperative_body(cl):
    """A job that heartbeats but outlives its deadline is cancelled at
    the next update() and recorded FAILED(TimeoutError), visible over
    the /3/Jobs REST surface."""
    from h2o_tpu.api import handlers
    from h2o_tpu.core.job import FAILED, Job

    def spin(j):
        while True:
            time.sleep(0.05)
            j.update(0.1, "spinning")

    job = cl.jobs.start(Job(description="budget",
                            deadline_secs=0.4), spin)
    with pytest.raises(TimeoutError, match="deadline"):
        job.join(15)
    assert job.status == FAILED
    d = handlers.get_job({}, job_id=str(job.key))["jobs"][0]
    assert d["status"] == "FAILED"
    assert "TimeoutError" in d["exception"]
    assert d["deadline_secs"] == 0.4


def test_chaos_stall_injector_trips_watchdog():
    from h2o_tpu.core import chaos
    from h2o_tpu.core.job import FAILED, Job, JobRegistry
    chaos.configure(stall_p=1.0, stall_secs=2.0, seed=0)
    reg = JobRegistry(max_workers=2, watchdog_interval=0.1)
    job = reg.start(Job(description="victim", stall_secs=0.4),
                    lambda j: "never-counts")
    with pytest.raises(TimeoutError):
        job.join(15)
    assert job.status == FAILED
    assert chaos.chaos().injected_stalls == 1


# -- REST observability ------------------------------------------------------

def test_resilience_route(cl):
    from h2o_tpu.api import handlers
    from h2o_tpu.core import chaos, resilience
    chaos.configure(persist_transient=1, seed=0)
    from h2o_tpu.core import persist
    persist.write_bytes("/tmp/h2o_tpu_res_route/blob", b"x")
    out = handlers.resilience_stats({})
    assert out["retry"]["retries"] >= 1
    assert out["chaos"]["injected_persist"] >= 1
    assert "expired_jobs" in out["watchdog"]
    assert out["watchdog"]["jobs_cap"] == cl.jobs.jobs_cap


def test_recovery_route_lists_checkpoint_state(cl, tmp_path):
    from h2o_tpu.api import handlers
    from h2o_tpu.core.recovery import Recovery
    from h2o_tpu.core.frame import Frame, Vec
    rng = np.random.default_rng(0)
    fr = Frame(["a"], [Vec(rng.normal(size=16).astype(np.float32))])
    rec = Recovery(str(tmp_path), "model", "m_rest")
    rec.begin({"ntrees": 4}, fr, extra={"algo": "gbm", "x": ["a"],
                                        "y": None})
    rec.save_iteration({"kind": "tree", "done": 2},
                       meta={"kind": "tree", "trees_done": 2})
    out = handlers.recovery_list({"recovery_dir": str(tmp_path)})
    assert len(out["pending"]) == 1
    p = out["pending"][0]
    assert p["job_id"] == "m_rest"
    assert p["has_iteration_checkpoint"] is True
    assert p["iteration"]["trees_done"] == 2
    with pytest.raises(Exception):      # no dir anywhere -> 400
        handlers.recovery_list({})
