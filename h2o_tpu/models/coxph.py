"""CoxPH — Cox proportional hazards survival regression.

Reference (hex/coxph/CoxPH.java:28): Newton-Raphson on the partial
log-likelihood with Efron (default) or Breslow tie handling
(CoxPHModel.java:41-43); inputs are ``stop_column`` (time, plus optional
``start_column`` for interval data) and an event response; outputs
coefficients, hazard ratios (exp_coef), se/z stats, loglik and concordance
(ModelMetricsRegressionCoxPH).

TPU-native: rows are sorted by time once on the host; the partial
log-likelihood is then a pure cumsum/segment-sum program over the sorted
risk sets, and Newton steps use jax.grad/jax.hessian of that scalar — the
reference's CoxPHTask MRTask accumulators (sumLogRiskEvents, rcumsumRisk,
…) become one differentiated XLA program.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from h2o_tpu.core.frame import Frame
from h2o_tpu.models import metrics as mm
from h2o_tpu.models.model import DataInfo, Model, ModelBuilder
from h2o_tpu.models.glm import expand_for_scoring, expansion_spec

EPS = 1e-10


def _neg_partial_loglik(beta, X, event, w, group, start_perm, start_cnt,
                        n_groups: int, efron: bool, max_ties: int):
    """Negative partial log-likelihood over rows sorted by DESCENDING stop
    time.

    group: tie-group id per row (same stop time ⇒ same group), increasing
    with the sort order (0 = largest time).  Left truncation (interval
    data): ``start_perm`` reorders rows by descending start time and
    ``start_cnt[g]`` counts rows whose start >= t_g — those are NOT yet at
    risk at t_g and their hazard mass is subtracted from the risk set.
    """
    eta = X @ beta
    r = w * jnp.exp(eta)
    # risk set sum for group g = cumsum of r over all rows with stop >= t_g
    cum_r = jnp.cumsum(r)
    grp_last = jax.ops.segment_max(jnp.arange(r.shape[0]), group,
                                   num_segments=n_groups)
    risk = cum_r[grp_last]                        # (G,) sum over risk set
    if start_perm is not None:
        cum_s = jnp.cumsum(r[start_perm])
        not_entered = jnp.where(start_cnt > 0,
                                cum_s[jnp.maximum(start_cnt - 1, 0)], 0.0)
        risk = risk - not_entered
    ev = w * event
    d = jax.ops.segment_sum(ev, group, num_segments=n_groups)       # events
    s_eta = jax.ops.segment_sum(ev * eta, group, num_segments=n_groups)
    if not efron:                                 # Breslow
        ll = s_eta - d * jnp.log(jnp.maximum(risk, EPS))
        return -jnp.sum(ll)
    # Efron: sum_{l=0..d-1} log(risk - (l/d) * tie_sum); weighted version
    # follows the reference's mean-subtraction per tied event
    tie_r = jax.ops.segment_sum(ev * jnp.exp(eta), group,
                                num_segments=n_groups)
    # integer tie counts bound the l-loop; max_ties is the true maximum
    # (computed by the caller), so no tie term is ever truncated
    cnt = jax.ops.segment_sum(event, group, num_segments=n_groups)

    def tie_term(g_risk, g_tie, g_d, g_cnt):
        ls = jnp.arange(max_ties, dtype=jnp.float32)
        act = ls < g_cnt
        frac = jnp.where(act, ls / jnp.maximum(g_cnt, 1.0), 0.0)
        t = jnp.log(jnp.maximum(g_risk - frac * g_tie, EPS))
        # scale to weighted event mass: mean log-term times weighted d
        return jnp.sum(jnp.where(act, t, 0.0)) / \
            jnp.maximum(g_cnt, 1.0) * g_d

    terms = jax.vmap(tie_term)(risk, tie_r, d, cnt)
    return -jnp.sum(s_eta - terms)


@functools.partial(jax.jit,
                   static_argnames=("n_groups", "efron", "max_ties",
                                    "truncated"))
def _newton_state(beta, X, event, w, group, start_perm, start_cnt,
                  n_groups: int, efron: bool, max_ties: int,
                  truncated: bool):
    f = lambda b: _neg_partial_loglik(  # noqa: E731
        b, X, event, w, group, start_perm if truncated else None,
        start_cnt, n_groups, efron, max_ties)
    nll = f(beta)
    g = jax.grad(f)(beta)
    H = jax.hessian(f)(beta)
    return nll, g, H


class CoxPHModel(Model):
    algo = "coxph"

    def predict_raw(self, frame: Frame):
        """Linear predictor eta (the reference scores lp; hazard ratios
        are exp(lp))."""
        out = self.output
        X = expand_for_scoring(frame, out["expansion_spec"])
        return (X - jnp.asarray(out["x_mean"])[None, :]) @ \
            jnp.asarray(out["coef"])

    def model_metrics(self, frame: Frame):
        out = self.output
        return mm.ModelMetrics("coxph", dict(
            loglik=out["loglik"], null_loglik=out["null_loglik"],
            concordance=out["concordance"],
            n_events=out["n_events"], iterations=out["iterations"]))


def _concordance(time, event, lp, cap: int = 4000) -> float:
    """Pairwise concordance (Harrell's C); subsampled beyond ``cap`` rows."""
    n = len(time)
    if n > cap:
        idx = np.random.default_rng(0).choice(n, cap, replace=False)
        time, event, lp = time[idx], event[idx], lp[idx]
    conc = ties = disc = 0
    order = np.argsort(time)
    time, event, lp = time[order], event[order], lp[order]
    for i in range(len(time)):
        if not event[i]:
            continue
        later = time > time[i]
        if not later.any():
            continue
        d = lp[later]
        conc += int((lp[i] > d).sum())
        ties += int((lp[i] == d).sum())
        disc += int((lp[i] < d).sum())
    tot = conc + ties + disc
    return (conc + 0.5 * ties) / tot if tot else 0.5


class CoxPH(ModelBuilder):
    algo = "coxph"
    model_cls = CoxPHModel

    def default_params(self) -> Dict:
        p = super().default_params()
        p.update(start_column=None, stop_column=None, ties="efron",
                 max_iterations=20, lre=9.0, use_all_factor_levels=False)
        return p

    def _fit(self, job, x, y, train: Frame, valid: Optional[Frame]):
        p = self.params
        stop_col = p.get("stop_column")
        if not stop_col:
            raise ValueError("CoxPH requires stop_column (event time)")
        x = [c for c in x if c not in (stop_col, p.get("start_column"))]
        di = DataInfo(train, x, y, mode="expanded",
                      weights=p.get("weights_column"),
                      use_all_factor_levels=bool(p["use_all_factor_levels"]),
                      impute_missing=True)
        X_full = di.matrix()
        yv = di.response()
        event = jnp.nan_to_num(yv)               # 1 = event, 0 = censored
        w_full = di.weights()
        time = train.vec(stop_col).as_float()
        valid_m = di.valid_mask() & ~jnp.isnan(time)

        # host: order rows by DESCENDING stop time, build tie groups
        t_np = np.asarray(time)[: train.nrows]
        v_np = np.asarray(valid_m)[: train.nrows]
        order = np.argsort(-t_np, kind="stable")
        order = order[v_np[order]]
        t_s = t_np[order]
        group = np.zeros(len(order), np.int32)
        if len(order) > 1:
            group[1:] = np.cumsum(t_s[1:] != t_s[:-1]).astype(np.int32)
        n_groups = int(group[-1]) + 1 if len(group) else 1

        Xh = np.asarray(X_full)[: train.nrows][order]
        x_mean = Xh.mean(axis=0)
        Xc = jnp.asarray(Xh - x_mean[None, :])    # centered (reference does)
        ev_np = np.asarray(event)[: train.nrows][order]
        ev = jnp.asarray(ev_np)
        ws = jnp.asarray(np.asarray(w_full)[: train.nrows][order])
        grp = jnp.asarray(group)
        efron = (p.get("ties") or "efron").lower() == "efron"
        P = Xc.shape[1]

        # exact tie-loop bound: the largest number of tied events; rounded
        # up to a power of two so re-fits with similar data reuse the jit
        max_cnt = int(np.bincount(group, weights=ev_np).max()) \
            if len(group) else 1
        max_ties = 1 << max(int(np.ceil(np.log2(max(max_cnt, 1)))), 0)

        # left truncation (start_column interval mode): rows enter the risk
        # set only after their start time
        truncated = bool(p.get("start_column"))
        if truncated:
            s_np = np.asarray(
                train.vec(p["start_column"]).as_float())[: train.nrows]
            s_s = s_np[order]                    # start times, stop-sorted
            start_perm = np.argsort(-s_s, kind="stable").astype(np.int32)
            s_sorted = s_s[start_perm]
            # t_g = the stop time of each tie group (first occurrence)
            _, first_idx = np.unique(group, return_index=True)
            t_g = t_s[first_idx]
            # rows with start >= t_g have not entered the risk set at t_g;
            # s_sorted is descending, so count via searchsorted on -s
            start_cnt = np.searchsorted(-s_sorted, -t_g,
                                        side="right").astype(np.int32)
            start_perm_j = jnp.asarray(start_perm)
            start_cnt_j = jnp.asarray(start_cnt)
        else:
            start_perm_j = jnp.zeros((Xc.shape[0],), jnp.int32)
            start_cnt_j = jnp.zeros((n_groups,), jnp.int32)

        beta = jnp.zeros((P,), jnp.float32)

        def state(b):
            return _newton_state(b, Xc, ev, ws, grp, start_perm_j,
                                 start_cnt_j, n_groups, efron, max_ties,
                                 truncated)

        nll0, _, _ = state(beta)
        nll_prev = float(nll0)
        it = 0
        for it in range(1, int(p["max_iterations"]) + 1):
            nll, g, H = state(beta)
            H = H + jnp.eye(P) * 1e-6
            step = jnp.linalg.solve(H, g)
            beta_new = beta - step
            nll_new, _, _ = state(beta_new)
            # step halving on divergence (reference does the same)
            halvings = 0
            while not np.isfinite(float(nll_new)) or \
                    float(nll_new) > float(nll) + 1e-9:
                step = step / 2
                beta_new = beta - step
                nll_new, _, _ = state(beta_new)
                halvings += 1
                if halvings > 20:
                    break
            beta = beta_new
            job.update(min(0.9, it / int(p["max_iterations"])),
                       f"iter {it} loglik {-float(nll_new):.5g}")
            if abs(nll_prev - float(nll_new)) < 10.0 ** (-float(p["lre"])) \
                    * max(1.0, abs(nll_prev)):
                nll_prev = float(nll_new)
                break
            nll_prev = float(nll_new)

        nll_f, g_f, H_f = state(beta)
        cov = np.linalg.inv(np.asarray(H_f) + np.eye(P) * 1e-8)
        se = np.sqrt(np.maximum(np.diag(cov), 0.0))
        coef = np.asarray(beta)
        lp = Xh @ coef - x_mean @ coef
        conc = _concordance(t_s[::-1], np.asarray(ev)[::-1].astype(bool),
                            lp[::-1])
        names = di.expanded_names
        out = dict(
            coef=coef, exp_coef=np.exp(coef), se_coef=se,
            z_coef=coef / np.maximum(se, EPS), coef_names=names,
            x_mean=x_mean, loglik=-float(nll_f),
            null_loglik=-float(nll0), iterations=it,
            n_events=int(np.asarray(ev).sum()),
            concordance=float(conc), ties=p["ties"],
            expansion_spec=expansion_spec(di))
        model = self.model_cls(self.model_id, dict(p), out)
        model.params["response_column"] = y
        model.output["training_metrics"] = model.model_metrics(train)
        return model
