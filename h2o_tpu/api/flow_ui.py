"""Built-in web UI served at / — the Flow analog.

The reference serves the prebuilt h2o-flow notebook JS at :54321
(h2o-web, SURVEY §2.3 "serve any static UI").  The TPU rebuild ships a
self-contained single-file dashboard over the same REST v3 surface:
cluster status, frames/models/jobs browsing, and a Rapids console —
no external assets (works in air-gapped TPU pods).
"""

FLOW_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>h2o-tpu</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f4f6f8; color: #1a1a2e; }
  header { background: #16213e; color: #fff; padding: 10px 24px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header span { color: #9fb3c8; font-size: 13px; }
  main { padding: 16px 24px; display: grid; gap: 16px;
         grid-template-columns: 1fr 1fr; }
  section { background: #fff; border-radius: 8px; padding: 12px 16px;
            box-shadow: 0 1px 3px rgba(0,0,0,.08); }
  section.wide { grid-column: 1 / -1; }
  h2 { font-size: 14px; margin: 0 0 8px; color: #0f3460;
       text-transform: uppercase; letter-spacing: .05em; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 4px 8px;
           border-bottom: 1px solid #e8ecf1; }
  th { color: #5a6a7a; font-weight: 600; }
  tr:hover td { background: #f0f4ff; }
  input[type=text] { width: 70%; padding: 6px 8px; font: 13px monospace;
           border: 1px solid #cbd5e1; border-radius: 4px; }
  button { padding: 6px 14px; border: 0; border-radius: 4px;
           background: #0f3460; color: #fff; cursor: pointer; }
  pre { background: #0b132b; color: #d7e3f4; padding: 10px;
        border-radius: 6px; font-size: 12px; overflow: auto;
        max-height: 220px; }
  .pill { display: inline-block; padding: 1px 8px; border-radius: 10px;
          font-size: 11px; background: #e0f2e9; color: #14532d; }
  .pill.run { background: #fef3c7; color: #92400e; }
  .pill.fail { background: #fee2e2; color: #991b1b; }
</style>
</head>
<body>
<header>
  <h1>h2o-tpu</h1><span id="cloud">connecting…</span>
</header>
<main>
  <section class="wide">
    <h2>Rapids console</h2>
    <input type="text" id="rap" placeholder="(mean (cols frame 'col'))"
           onkeydown="if(event.key==='Enter')runRapids()">
    <button onclick="runRapids()">Run</button>
    <pre id="rapout">&gt; results appear here</pre>
  </section>
  <section><h2>Frames</h2><table id="frames"></table></section>
  <section><h2>Models</h2><table id="models"></table></section>
  <section class="wide"><h2>Jobs</h2><table id="jobs"></table></section>
</main>
<script>
const J = p => fetch(p).then(r => r.json());
function rows(el, head, data) {
  el.innerHTML = '<tr>' + head.map(h => `<th>${h}</th>`).join('') +
    '</tr>' + data.map(r => '<tr>' +
      r.map(c => `<td>${c ?? ''}</td>`).join('') + '</tr>').join('');
}
async function refresh() {
  try {
    const c = await J('/3/Cloud');
    document.getElementById('cloud').textContent =
      `${c.cloud_name} — ${c.cloud_size} nodes — v${c.version}`;
    const fr = await J('/3/Frames');
    rows(document.getElementById('frames'), ['key', 'rows', 'cols'],
      fr.frames.map(f => [f.frame_id.name, f.row_count ?? f.rows,
                          f.column_count]));
    const mo = await J('/3/Models');
    rows(document.getElementById('models'), ['key', 'algo', 'category'],
      mo.models.map(m => [m.model_id.name, m.algo,
                          m.output?.model_category]));
    const jb = await J('/3/Jobs');
    rows(document.getElementById('jobs'),
      ['key', 'description', 'status', 'progress'],
      jb.jobs.map(j => [j.key?.name, j.description,
        `<span class="pill ${j.status === 'RUNNING' ? 'run' :
           j.status === 'FAILED' ? 'fail' : ''}">${j.status}</span>`,
        Math.round((j.progress ?? 0) * 100) + '%']));
  } catch (e) {
    document.getElementById('cloud').textContent = 'error: ' + e;
  }
}
async function runRapids() {
  const ast = document.getElementById('rap').value;
  const out = document.getElementById('rapout');
  try {
    const r = await fetch('/99/Rapids', {method: 'POST',
      headers: {'Content-Type': 'application/x-www-form-urlencoded'},
      body: 'ast=' + encodeURIComponent(ast) + '&session_id=_flow'});
    out.textContent = '> ' + ast + '\\n' +
      JSON.stringify(await r.json(), null, 2);
    refresh();
  } catch (e) { out.textContent = 'error: ' + e; }
}
refresh();
setInterval(refresh, 4000);
</script>
</body>
</html>
"""


def register_routes():
    from h2o_tpu.api.server import route

    @route("GET", r"/(?:flow/?(?:index\.html)?)?")
    def flow_index(params):
        return ("text/html; charset=utf-8", FLOW_HTML.encode())

    @route("GET", r"/3/")
    def api_index(params):
        from h2o_tpu.api.handlers import endpoints
        return endpoints(params)
