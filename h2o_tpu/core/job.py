"""Async job tracking (reference: water/Job.java, water/api/JobsHandler.java).

Jobs run on a host thread pool (the FJ-pool analog for *control* work — the
actual compute is dispatched to the TPU mesh inside the job body).  Progress,
cancellation, exception propagation, and DKV visibility match the reference's
Job<T> semantics.

Resilience (core/resilience.py):
- per-job DEADLINES: a job may declare ``deadline_secs`` (or inherit the
  registry default); a watchdog thread expires jobs that outlive it,
  marking them FAILED with a ``TimeoutError`` and reclaiming the pool
  slot so later jobs are never starved behind a hang;
- STALL detection: ``update()`` doubles as a progress heartbeat; a job
  with no heartbeat inside its ``stall_secs`` window is expired the same
  way (the reference's analogous guard is the client-disconnect
  watchdog, water/Job.java cancel plumbing);
- bounded registry: terminal jobs past ``jobs_cap`` are LRU-evicted so a
  long-lived server doesn't leak one entry per job.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from h2o_tpu.core.lockwitness import make_lock
from h2o_tpu.core.log import get_logger
from h2o_tpu.core.store import Key

log = get_logger("job")

CREATED = "CREATED"
RUNNING = "RUNNING"
DONE = "DONE"
CANCELLED = "CANCELLED"
FAILED = "FAILED"
# INTERRUPTED is terminal FOR THIS JOB OBJECT but not for the work: the
# job was stopped by a device/slice loss (or a membership quiesce) with
# its checkpoints intact, and the recovery protocol replays it as a NEW
# job on the reformed mesh (``requeued_as`` links the two).  Distinct
# from FAILED so dashboards/soaks can tell "the work died" from "the
# work moved".
INTERRUPTED = "INTERRUPTED"
TERMINAL = (DONE, CANCELLED, FAILED, INTERRUPTED)


class JobCancelledException(Exception):
    pass


class JobInterruptedException(Exception):
    """Raised inside a job body at its next ``update()`` after a
    membership interrupt, and to joiners of an INTERRUPTED job that
    carries no underlying device-loss exception."""


class Job:
    """A tracked unit of async work producing a DKV-visible result."""

    # priority bands (reference: water/H2O.java:1470-1560 FJPS[0..126] —
    # user MR work 0-118, system work 119+ can never be starved by it)
    USER_PRIORITY = 50
    SYSTEM_PRIORITY = 119

    def __init__(self, dest: Optional[str] = None, description: str = "",
                 dest_type: str = "Key<Frame>",
                 priority: int = USER_PRIORITY,
                 deadline_secs: Optional[float] = None,
                 stall_secs: Optional[float] = None,
                 tenant: Optional[str] = None):
        from h2o_tpu.core.tenant import current_tenant
        self.priority = int(priority)
        # inherit the submitting thread's tenant context so everything a
        # tenant-tagged body spawns (grid members, AutoML builds, stream
        # refreshes) stays attributed to the same tenant
        self.tenant = tenant if tenant is not None else current_tenant()
        # True while the job waits in the fair-share admission queue —
        # it holds no mesh state yet, so quiesce() skips it and it
        # admits on the survivor mesh after a reform
        self._admission_queued = False
        self._admission_slot = False
        self.key = Key.make("job")
        self.dest = Key(dest) if dest else Key.make("result")
        self.dest_type = dest_type
        self.description = description
        self.status = CREATED
        self.progress = 0.0
        self.progress_msg = ""
        self.warnings: list = []
        self.exception: Optional[BaseException] = None
        self.start_time = 0.0
        self.end_time = 0.0
        # None = inherit the registry default; 0 = explicitly unbounded
        self.deadline_secs = deadline_secs
        self.stall_secs = stall_secs
        self.last_progress = 0.0
        self._timed_out = False
        self._cancel_requested = threading.Event()
        self._interrupt_requested = threading.Event()
        self.interrupted_by = ""
        # set by the recovery protocol once the work is replayed on the
        # reformed mesh: the key of the resumed job/model
        self.requeued_as: Optional[str] = None
        self._done = threading.Event()
        # serializes the terminal transition between the worker thread
        # and the watchdog (core/job.py JobRegistry._expire)
        self._state_lock = make_lock("job.Job._state_lock")
        self.result: Any = None

    # -- body-side API ------------------------------------------------------

    def update(self, progress: float, msg: str = "") -> None:
        """Called from inside the job body; doubles as the watchdog's
        progress heartbeat and raises if cancel was requested
        (cooperative cancellation, like the reference's Job.stop_requested)."""
        self.progress = float(progress)
        self.last_progress = time.time()
        if msg:
            self.progress_msg = msg
        if self._interrupt_requested.is_set():
            raise JobInterruptedException(
                f"{self.description}: {self.interrupted_by or 'interrupted'}")
        if self._cancel_requested.is_set():
            raise JobCancelledException(self.description)

    def warn(self, msg: str) -> None:
        """Attach a client-visible warning (reference Job.warn ->
        JobV3.warnings; the stock h2o-py client re-raises each entry via
        warnings.warn when the job finishes, h2o-py/h2o/job.py:79-81)."""
        if msg not in self.warnings:
            self.warnings.append(msg)

    @property
    def stop_requested(self) -> bool:
        return self._cancel_requested.is_set()

    # -- control-side API ---------------------------------------------------

    def cancel(self) -> None:
        self._cancel_requested.set()

    def interrupt(self, cause: str = "") -> None:
        """Request a RESUMABLE stop (membership quiesce): the body exits
        at its next ``update()`` with the job marked INTERRUPTED, its
        recovery checkpoints intact, ready for replay on a new mesh.
        Also sets the cooperative-cancel event so bodies polling
        ``stop_requested`` exit too (run() reclassifies their
        cancellation as an interrupt)."""
        self.interrupted_by = cause or "membership interrupt"
        self._interrupt_requested.set()
        self._cancel_requested.set()

    def join(self, timeout: Optional[float] = None) -> Any:
        if not self._done.wait(timeout):
            raise TimeoutError(f"job {self.key} still running")
        if self.status == FAILED:
            exc = self.exception
            if exc is None:
                # defensive: a FAILED job must carry its cause; surface
                # the inconsistency instead of raising TypeError(None)
                raise RuntimeError(
                    f"job {self.key} FAILED with no recorded exception")
            # Re-raise a same-type clone CHAINED from the original, so the
            # worker-thread traceback survives intact on the cause instead
            # of being mutated by every joiner re-raising the shared
            # exception object.
            try:
                clone = type(exc)(*exc.args)
            except Exception:        # exotic ctor signature — raise as-is
                raise exc
            raise clone from exc
        if self.status == CANCELLED:
            raise JobCancelledException(self.description)
        if self.status == INTERRUPTED:
            exc = self.exception
            if exc is not None:
                # surface the classified device loss itself, so callers
                # (and is_device_loss) see what actually happened
                try:
                    clone = type(exc)(*exc.args)
                except Exception:
                    raise exc
                raise clone from exc
            raise JobInterruptedException(
                f"{self.description}: {self.interrupted_by}")
        return self.result

    @property
    def is_running(self) -> bool:
        return self.status in (CREATED, RUNNING)

    def to_dict(self) -> Dict[str, Any]:
        """REST /3/Jobs schema-shaped summary."""
        ms = lambda t: int(t * 1000) if t else 0
        return {
            "__meta": {"schema_version": 3, "schema_name": "JobV3",
                       "schema_type": "Job"},
            "key": {"name": str(self.key), "type": "Key<Job>",
                    "URL": f"/3/Jobs/{self.key}"},
            "dest": {"name": str(self.dest), "type": self.dest_type,
                     "URL": f"/3/Models/{self.dest}"
                     if "Model" in self.dest_type
                     else f"/3/Frames/{self.dest}"},
            "description": self.description,
            "status": self.status,
            "progress": self.progress,
            "progress_msg": self.progress_msg,
            "start_time": ms(self.start_time),
            "msec": ms((self.end_time or time.time()) - self.start_time)
            if self.start_time else 0,
            "warnings": list(self.warnings),
            "exception": repr(self.exception) if self.exception else None,
            "stacktrace": None,
            "ready_for_view": self.status == "DONE",
            "auto_recoverable": self.status == INTERRUPTED,
            "interrupted_by": self.interrupted_by or None,
            "requeued_as": self.requeued_as,
            # resilience surface (deadline/watchdog state)
            "deadline_secs": self.deadline_secs,
            "stall_secs": self.stall_secs,
            "last_progress": ms(self.last_progress),
            "timed_out": self._timed_out,
            "tenant": self.tenant,
            "admission_queued": self._admission_queued,
        }


#: retire token — a worker that dequeues it exits iff the pool is over
#: its target size (a concurrent grow() simply makes the token a no-op)
_RETIRE = object()


class ResizablePool:
    """An OWNED daemon-thread work pool with first-class grow/shrink.

    Replaces the previous approach of reaching into
    ``ThreadPoolExecutor`` privates (``_shutdown_lock`` /
    ``_max_workers`` / ``_adjust_thread_count``) for watchdog slot
    compensation, which any CPython point release could silently break.
    Semantics the registry depends on:

    - ``submit`` never blocks: tasks queue and lazily spawn workers up
      to ``_max_workers`` (same ramp-up as the stdlib executor);
    - ``grow`` adds one slot AND spawns its worker immediately — the
      compensation path runs while the expired job's thread is still
      wedged in its body, so capacity must not wait for the next
      submit;
    - ``shrink`` lowers the target (floor 1) and enqueues a retire
      token; whichever worker dequeues it exits only if the pool is
      STILL over target, so grow/shrink races settle at the target
      size instead of deadlocking or leaking threads.

    ``_max_workers`` stays a public-in-practice attribute name because
    the soak asserts slot conservation through it.
    """

    def __init__(self, max_workers: int, thread_name_prefix: str = "h2o-pool"):
        self._tasks: "queue.SimpleQueue" = queue.SimpleQueue()
        self._size_lock = threading.Lock()
        self._max_workers = max(1, int(max_workers))
        self._live = 0
        self._spawned = 0
        self._prefix = thread_name_prefix

    @property
    def max_workers(self) -> int:
        return self._max_workers

    @property
    def live_workers(self) -> int:
        return self._live

    def submit(self, fn: Callable[..., Any], *args: Any) -> None:
        self._tasks.put((fn, args))
        self._ensure_worker()

    def grow(self) -> bool:
        """Add one worker slot, effective immediately (compensation for
        a wedged thread that still occupies one of the old slots)."""
        with self._size_lock:
            self._max_workers += 1
        self._ensure_worker()
        return True

    def shrink(self) -> None:
        """Give back a compensated slot once the wedged thread exits."""
        with self._size_lock:
            if self._max_workers <= 1:
                return
            self._max_workers -= 1
        self._tasks.put(_RETIRE)

    def _ensure_worker(self) -> None:
        with self._size_lock:
            if self._live >= self._max_workers:
                return
            self._live += 1
            self._spawned += 1
            n = self._spawned
        t = threading.Thread(target=self._worker, daemon=True,
                             name=f"{self._prefix}-{n}")
        t.start()

    def _worker(self) -> None:
        while True:
            item = self._tasks.get()
            if item is _RETIRE:
                with self._size_lock:
                    if self._live > self._max_workers:
                        self._live -= 1
                        return
                continue  # grow() raced the token — stay alive, drop it
            fn, args = item
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — job bodies report their
                # own outcomes; a leak to here must not kill the worker
                log.exception("pool worker: task leaked an exception")


class JobRegistry:
    """Two-band priority scheduler (the FJPS[0..126] analog, water/
    H2O.java:1470-1560): user jobs (model builds, parses) share a bounded
    pool; jobs at SYSTEM_PRIORITY and above run on a reserved pool so
    control work (recovery resume, exports, admin) is never starved
    behind long model builds — the same non-starvation invariant the
    reference's leveled ForkJoin pools provide.

    A daemon watchdog enforces per-job deadlines and stall windows (see
    the module docstring); ``jobs_cap`` bounds the registry by LRU-
    evicting terminal jobs (REST /3/Jobs simply stops listing them, the
    same observable behavior as the reference's expiring job keys).
    """

    def __init__(self, max_workers: int = 8, system_workers: int = 2,
                 default_deadline_secs: float = 0.0,
                 default_stall_secs: float = 0.0,
                 watchdog_interval: float = 0.5,
                 jobs_cap: int = 512):
        self._jobs: Dict[Key, Job] = {}
        self._pool = ResizablePool(max_workers,
                                   thread_name_prefix="h2o-job")
        self._sys_pool = ResizablePool(system_workers,
                                       thread_name_prefix="h2o-sysjob")
        self._lock = make_lock("job.JobRegistry._lock")
        self._admission = None
        self.default_deadline_secs = float(default_deadline_secs)
        self.default_stall_secs = float(default_stall_secs)
        self.watchdog_interval = float(watchdog_interval)
        self.jobs_cap = int(jobs_cap)
        self.expired_count = 0
        self.evicted_count = 0
        self._watchdog: Optional[threading.Thread] = None

    @property
    def admission(self):
        """The fair-share admission queue (created on first touch; a
        cluster that never registers a Tenant never pays for it)."""
        if self._admission is None:
            from h2o_tpu.core.tenant import FairShareAdmission
            with self._lock:
                if self._admission is None:
                    self._admission = FairShareAdmission(self)
        return self._admission

    # -- watchdog -----------------------------------------------------------

    def _ensure_watchdog(self) -> None:
        if self._watchdog is not None and self._watchdog.is_alive():
            return
        t = threading.Thread(target=self._watch, daemon=True,
                             name="h2o-job-watchdog")
        self._watchdog = t
        t.start()

    def _watch(self) -> None:
        while True:
            time.sleep(self.watchdog_interval)
            now = time.time()
            for job in self.list():
                if job.status != RUNNING or job._timed_out:
                    continue
                dl = job.deadline_secs if job.deadline_secs is not None \
                    else self.default_deadline_secs
                if dl and now - job.start_time > dl:
                    self._expire(job, f"deadline of {dl:g}s exceeded")
                    continue
                stall = job.stall_secs if job.stall_secs is not None \
                    else self.default_stall_secs
                beat = job.last_progress or job.start_time
                if stall and now - beat > stall:
                    self._expire(job, "no progress heartbeat for "
                                      f"{stall:g}s (stall window)")

    def _expire(self, job: Job, why: str) -> None:
        """Watchdog-side terminal transition: FAILED + TimeoutError, done
        event set (joiners unblock NOW), cooperative cancel requested so
        the body exits at its next update(), and the pool compensated in
        case the body never does."""
        with job._state_lock:
            if job.status in TERMINAL:
                return
            log.warning("watchdog: expiring job %s (%s): %s", job.key,
                        job.description, why)
            job._timed_out = True
            job.exception = TimeoutError(
                f"job {job.key} ({job.description}): {why}")
            job.cancel()
            job.status = FAILED
            job.end_time = time.time()
            self.expired_count += 1
            job._done.set()
        pool = self._sys_pool if job.priority >= Job.SYSTEM_PRIORITY \
            else self._pool
        if pool.grow():
            job._compensated_pool = pool

    # -- registry bound -----------------------------------------------------

    def _evict_terminal(self) -> None:
        """LRU-evict terminal jobs past jobs_cap (oldest end_time first);
        live jobs are never evicted."""
        with self._lock:
            over = len(self._jobs) - self.jobs_cap
            if over <= 0:
                return
            dead = sorted((j for j in self._jobs.values()
                           if j.status in TERMINAL),
                          key=lambda j: j.end_time)
            for j in dead[:over]:
                del self._jobs[j.key]
                self.evicted_count += 1

    # -- scheduling ---------------------------------------------------------

    def start(self, job: Job, body: Callable[[Job], Any]) -> Job:
        """Register and schedule a job.  Tenant-tagged user jobs on a
        cluster with registered tenants pass the fair-share admission
        queue first (which may raise a classified
        ``AdmissionRejected`` — the 429 path); everything else (system
        band, untagged, or nested submissions from a body that already
        holds an admission slot) dispatches directly, so a grid/AutoML
        run costs exactly ONE logical admission."""
        from h2o_tpu.core.tenant import needs_admission
        with self._lock:
            self._jobs[job.key] = job
        self._evict_terminal()
        self._ensure_watchdog()
        runner = self._runner(job, body)
        if needs_admission(job):
            self.admission.submit(job, runner)
        else:
            self._dispatch(job, runner)
        return job

    def _dispatch(self, job: Job, runner: Callable[[], None]) -> None:
        pool = self._sys_pool if job.priority >= Job.SYSTEM_PRIORITY \
            else self._pool
        pool.submit(runner)

    def _runner(self, job: Job,
                body: Callable[[Job], Any]) -> Callable[[], None]:
        def run():
            from h2o_tpu.core.diag import TimeLine
            from h2o_tpu.core import tenant as tenantmod
            TimeLine.record("job", "start", key=str(job.key),
                            description=job.description)
            job.status = RUNNING
            job.start_time = time.time()
            job.last_progress = job.start_time
            # pool worker threads are reused, so the body establishes
            # its own tenant context unconditionally (and restores the
            # previous one in finally)
            ctx_token = tenantmod._enter_job(job.tenant)
            try:
                from h2o_tpu.core.chaos import chaos
                if chaos().enabled:
                    chaos().maybe_fail_job(job.description)
                    chaos().maybe_stall(job.description)
                result = body(job)
                with job._state_lock:
                    if not job._timed_out:
                        job.result = result
                        job.status = DONE
                        job.progress = 1.0
            except JobInterruptedException as e:
                with job._state_lock:
                    if not job._timed_out:
                        job.status = INTERRUPTED
                        job.exception = None
                log.warning("job %s interrupted (%s): %s", job.key,
                            job.description, e)
            except JobCancelledException:
                interrupted = job._interrupt_requested.is_set()
                with job._state_lock:
                    if not job._timed_out:
                        job.status = INTERRUPTED if interrupted \
                            else CANCELLED
            except BaseException as e:  # noqa: BLE001 — propagate to joiner
                from h2o_tpu.core.oom import is_device_loss
                lost = is_device_loss(e)
                with job._state_lock:
                    if not job._timed_out:
                        job.status = INTERRUPTED if lost else FAILED
                        job.exception = e
                        if lost and not job.interrupted_by:
                            job.interrupted_by = f"device loss: {e}"
                if lost:
                    log.warning("job %s interrupted by device/slice "
                                "loss: %s", job.key, e)
                    try:
                        from h2o_tpu.core.membership import monitor
                        monitor().note_loss(e, source=f"job:{job.key}")
                    except Exception:  # noqa: BLE001 — loss reporting
                        # must never mask the job's own outcome
                        log.exception("membership loss report failed")
                else:
                    log.error("job %s failed: %s\n%s", job.key, e,
                              traceback.format_exc())
            finally:
                tenantmod._exit_job(ctx_token)
                with job._state_lock:
                    if not job._timed_out:
                        job.end_time = time.time()
                pool = getattr(job, "_compensated_pool", None)
                if pool is not None:
                    pool.shrink()
                if self._admission is not None:
                    self._admission.release(job)
                TimeLine.record("job", "end", key=str(job.key),
                                status=job.status)
                job._done.set()

        return run

    def run_sync(self, job: Job, body: Callable[[Job], Any]) -> Any:
        self.start(job, body)
        return job.join()

    def quiesce(self, cause: str = "membership reform",
                wait_secs: float = 15.0, exclude=()) -> list:
        """Interrupt every live job (resumably — checkpoints intact) and
        wait a bounded window for their bodies to exit; the membership
        recovery protocol calls this before ``Cloud.reform`` so no job
        body dispatches onto the dying mesh mid-resize.  Returns the
        interrupted jobs; a body wedged past the window is left to die
        on its own dispatch failure (the watchdog compensates its pool
        slot)."""
        victims = []
        for job in self.list():
            if str(job.key) in exclude:
                continue
            # fair-share-queued jobs hold no mesh state yet — they ride
            # out the reform in their queue and admit on the survivor
            # mesh, so interrupting them would only destroy queued work
            if job._admission_queued:
                continue
            if job.status in (CREATED, RUNNING):
                job.interrupt(cause)
                victims.append(job)
        deadline = time.time() + max(0.0, wait_secs)
        for job in victims:
            job._done.wait(max(0.0, deadline - time.time()))
        return victims

    def get(self, key: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(Key(key))

    def list(self) -> list:
        with self._lock:
            return list(self._jobs.values())
