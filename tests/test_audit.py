"""graftaudit — planted-defect fixtures for the GL7xx IR tier and the
GL8xx runtime lock witness (lint/audit.py + core/lockwitness.py).

Each planted defect must produce exactly ONE finding and each negative
twin must stay clean — the same contract the AST-tier fixture table in
test_graftlint.py enforces, applied to evidence the AST cannot see:
compiled executables and witnessed lock acquisitions.

Isolation: the IR tests enable ``H2O_TPU_AUDIT`` per-test and reset the
global recorders on exit; the witness tests plant their inversions in
PRIVATE :class:`WitnessRegistry` instances.  Neither ever dirties the
process-wide state that test_lint_resilience's real-package clean run
checks.
"""

import os
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from h2o_tpu.core import lockwitness
from h2o_tpu.core.exec_store import ExecStore
from h2o_tpu.lint import audit


@pytest.fixture()
def ir_audit(monkeypatch):
    """Audit recording on, clean global recorders before AND after —
    the after-reset keeps the mid-suite package-wide lint run blind to
    anything planted here."""
    monkeypatch.setenv("H2O_TPU_AUDIT", "1")
    audit.reset()
    yield
    audit.reset()


def _compile(store, phase, name, build, x, **kw):
    with warnings.catch_warnings():
        # jax warns when a declared donation is dropped — that warning
        # IS the planted defect, not test noise
        warnings.simplefilter("ignore")
        return store.get_or_build(phase, (name, x.shape[0]), build,
                                  args=(x,), **kw)


# -- GL701: donation declared but dropped by XLA -----------------------------

def test_gl701_donation_dropped_fires_once(ir_audit):
    st = ExecStore()
    x = jnp.arange(1024.0)
    # planted: output shape != donated input shape → XLA drops the alias
    _compile(st, "munge", "gl701_bad",
             lambda: (lambda a: jnp.concatenate([a, a])), x,
             donate_argnums=(0,), donate=True)
    # negative twin: same-shape update → alias honored
    _compile(st, "munge", "gl701_ok",
             lambda: (lambda a: a * 2.0), x,
             donate_argnums=(0,), donate=True)
    found = [f for f in audit.ir_findings() if f.rule == "GL701"]
    assert len(found) == 1, [f.render() for f in found]
    assert "gl701_bad" in found[0].detail
    assert found[0].severity == "error"


def test_gl701_silent_when_donation_not_resolved(ir_audit):
    # donation declared but resolved OFF (the CPU default) — nothing to
    # audit: the executable legitimately carries no aliasing
    st = ExecStore()
    x = jnp.arange(64.0)
    _compile(st, "munge", "gl701_off",
             lambda: (lambda a: jnp.concatenate([a, a])), x,
             donate_argnums=(0,), donate=False)
    assert not [f for f in audit.ir_findings() if f.rule == "GL701"]


# -- GL702: host transfer inside a steady-state executable -------------------

def test_gl702_host_callback_in_steady_state(ir_audit):
    st = ExecStore()
    x = jnp.arange(256.0)

    def build():
        def f(a):
            b = jax.pure_callback(lambda v: np.asarray(v) + 1.0,
                                  jax.ShapeDtypeStruct(a.shape, a.dtype),
                                  a)
            return b * 2.0
        return f

    _compile(st, "munge", "gl702_bad", build, x)
    # negative twin: pure device kernel in the same phase
    _compile(st, "munge", "gl702_ok",
             lambda: (lambda a: jnp.cumsum(a)), x)
    found = [f for f in audit.ir_findings() if f.rule == "GL702"]
    assert len(found) == 1, [f.render() for f in found]
    assert "gl702_bad" in found[0].detail
    assert "callback" in found[0].message


def test_gl702_silent_outside_steady_phases(ir_audit):
    # the same callback in a non-steady phase (model scoring may
    # legitimately call host code) is not a finding
    st = ExecStore()
    x = jnp.arange(256.0)

    def build():
        def f(a):
            return jax.pure_callback(
                lambda v: np.asarray(v) + 1.0,
                jax.ShapeDtypeStruct(a.shape, a.dtype), a)
        return f

    _compile(st, "score", "gl702_other_phase", build, x)
    assert not [f for f in audit.ir_findings() if f.rule == "GL702"]


# -- GL703: sharded input, replicated output >= global size ------------------

def _nodes_mesh():
    return Mesh(np.array(jax.devices()), ("nodes",))


def test_gl703_replicated_blowup(ir_audit):
    st = ExecStore()
    mesh = _nodes_mesh()
    xs = jax.device_put(jnp.arange(4096.0),
                        NamedSharding(mesh, P("nodes")))
    # planted: the kernel forces a fully-replicated output the size of
    # the sharded input's GLOBAL array — an accidental all-gather
    _compile(st, "tree_block", "gl703_bad",
             lambda: jax.jit(lambda a: a + 1.0,
                             out_shardings=NamedSharding(mesh, P())), xs)
    # negative twin: output keeps the input's sharding
    _compile(st, "tree_block", "gl703_ok",
             lambda: jax.jit(lambda a: a + 1.0,
                             out_shardings=NamedSharding(mesh,
                                                         P("nodes"))), xs)
    found = [f for f in audit.ir_findings() if f.rule == "GL703"]
    assert len(found) == 1, [f.render() for f in found]
    assert "gl703_bad" in found[0].detail


def test_gl703_small_replicated_scalar_ok(ir_audit):
    # a replicated REDUCTION (scalar) is the normal shape of tree_block
    # results — far below the input's global size, not a finding
    st = ExecStore()
    mesh = _nodes_mesh()
    xs = jax.device_put(jnp.arange(4096.0),
                        NamedSharding(mesh, P("nodes")))
    _compile(st, "tree_block", "gl703_reduce",
             lambda: jax.jit(lambda a: jnp.sum(a),
                             out_shardings=NamedSharding(mesh, P())), xs)
    assert not [f for f in audit.ir_findings() if f.rule == "GL703"]


def test_gl703_slices_replicated_output(ir_audit):
    """Two-level-mesh variant: an output partitioned over the inner
    ``nodes`` axis but NOT over ``slices`` replicates the row data once
    per slice — each copy crossed the DCN.  The negative twin shards
    over the product axis (Cloud.data_pspec's two-level spec) and must
    stay clean."""
    st = ExecStore()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4),
                ("slices", "nodes"))
    xs = jax.device_put(jnp.arange(4096.0),
                        NamedSharding(mesh, P(("slices", "nodes"))))
    # planted: drops the slices axis → full per-slice replica
    _compile(st, "munge", "gl703s_bad",
             lambda: jax.jit(lambda a: a + 1.0,
                             out_shardings=NamedSharding(mesh,
                                                         P("nodes"))), xs)
    # negative twin: keeps the two-level row sharding
    _compile(st, "munge", "gl703s_ok",
             lambda: jax.jit(
                 lambda a: a + 1.0,
                 out_shardings=NamedSharding(
                     mesh, P(("slices", "nodes")))), xs)
    found = [f for f in audit.ir_findings() if f.rule == "GL703"]
    assert len(found) == 1, [f.render() for f in found]
    assert found[0].detail == "slices-replicated:munge:gl703s_bad"
    assert "slices" in found[0].message


# -- GL704: recompile churn --------------------------------------------------

def test_gl704_recompile_churn(ir_audit, monkeypatch):
    monkeypatch.setenv("H2O_TPU_AUDIT_CHURN", "4")
    for n in range(6):
        audit.note_compile("munge:churny", f"aval{n}")
    for n in range(2):
        audit.note_compile("munge:steady", f"aval{n}")
    found = [f for f in audit.ir_findings() if f.rule == "GL704"]
    assert len(found) == 1, [f.render() for f in found]
    assert "churny" in found[0].detail
    assert audit.compile_counts()["munge:churny"]["distinct_aval_keys"] == 6


# -- GL801: witnessed lock-order inversion -----------------------------------

def test_gl801_planted_inversion():
    """Two threads take (memory, exec_store) locks in opposite orders —
    sequentially, so no real deadlock, but the witnessed graph carries
    the cycle and GL801 must report it with BOTH acquisition stacks."""
    reg = lockwitness.WitnessRegistry()
    mem = lockwitness.make_rlock("memory.MemoryManager._lock",
                                 _registry=reg)
    exe = lockwitness.make_rlock("exec_store.ExecStore._lock",
                                 _registry=reg)

    def forward():
        with mem:
            with exe:
                pass

    def backward():
        with exe:
            with mem:
                pass

    for target in (forward, backward):
        t = threading.Thread(target=target)
        t.start()
        t.join()

    found = audit.witness_findings(reg)
    cycles = [f for f in found if f.rule == "GL801"]
    assert len(cycles) == 1, [f.render() for f in found]
    f = cycles[0]
    assert f.detail == ("cycle:exec_store.ExecStore._lock"
                        "<>memory.MemoryManager._lock")
    # both witnessed edges, each with its captured stack
    assert f.message.count("--- witnessed") == 2
    assert "forward" in f.message and "backward" in f.message


def test_gl801_consistent_order_is_clean():
    reg = lockwitness.WitnessRegistry()
    a = lockwitness.make_lock("a", _registry=reg)
    b = lockwitness.make_lock("b", _registry=reg)
    for _ in range(3):
        with a:
            with b:
                pass
    assert reg.find_cycles() == []
    assert not audit.witness_findings(reg)
    assert reg.name_edges() == {("a", "b"): 3}


def test_gl801_same_name_instances_not_a_cycle():
    """Many Job._state_lock INSTANCES share one name; job A's lock
    inside job B's must not read as a self-cycle — the graph is keyed
    on instances, names are only for display."""
    reg = lockwitness.WitnessRegistry()
    j1 = lockwitness.make_lock("job.Job._state_lock", _registry=reg)
    j2 = lockwitness.make_lock("job.Job._state_lock", _registry=reg)
    with j1:
        with j2:
            pass
    with j2:
        with j1:
            pass
    # instance-order inversion IS reported (it is a real cycle) but a
    # single nesting of two same-named instances alone is not
    reg2 = lockwitness.WitnessRegistry()
    k1 = lockwitness.make_lock("job.Job._state_lock", _registry=reg2)
    k2 = lockwitness.make_lock("job.Job._state_lock", _registry=reg2)
    with k1:
        with k2:
            pass
    assert reg2.find_cycles() == []
    assert len(reg.find_cycles()) == 1


# -- GL802: device dispatch while holding a witnessed lock -------------------

def test_gl802_dispatch_under_lock():
    reg = lockwitness.WitnessRegistry()
    lk = lockwitness.make_lock("memory.MemoryManager._lock",
                               _registry=reg)
    with lk:
        reg.note_device_dispatch("munge:frame_slice")
    reg.note_device_dispatch("munge:frame_slice")  # lock-free: clean
    found = audit.witness_findings(reg)
    assert len(found) == 1, [f.render() for f in found]
    f = found[0]
    assert f.rule == "GL802"
    assert f.detail == ("dispatch-under-lock:"
                        "memory.MemoryManager._lock:munge:frame_slice")
    assert "test_gl802_dispatch_under_lock" in f.message


# -- witness mechanics -------------------------------------------------------

def test_witness_rlock_reentry_records_no_edge():
    reg = lockwitness.WitnessRegistry()
    r = lockwitness.make_rlock("r", _registry=reg)
    with r:
        with r:  # re-entry: count bump, no self-edge
            pass
    assert reg.name_edges() == {}
    assert reg.stats()["acquisitions"] == 2


def test_witness_off_returns_plain_primitives(monkeypatch):
    monkeypatch.setenv("H2O_TPU_LOCK_WITNESS", "0")
    lk = lockwitness.make_lock("x")
    rk = lockwitness.make_rlock("y")
    assert not isinstance(lk, lockwitness._WitnessLock)
    assert not isinstance(rk, lockwitness._WitnessLock)
    with lk:
        pass
    with rk:
        with rk:
            pass


def test_witness_acquire_release_api():
    reg = lockwitness.WitnessRegistry()
    lk = lockwitness.make_lock("api", _registry=reg)
    assert lk.acquire()
    assert lk.locked()
    lk.release()
    assert not lk.locked()
    assert lk.acquire(blocking=False)
    lk.release()


# -- tiers, cross-check, payload ---------------------------------------------

def test_tier_of():
    assert audit.tier_of("GL701") == "ir"
    assert audit.tier_of("GL801") == "runtime"
    assert audit.tier_of("GL402") == "ast"


def test_gl7xx_gl8xx_registered():
    from h2o_tpu.lint import all_rules
    ids = set(all_rules())
    assert {"GL701", "GL702", "GL703", "GL704",
            "GL801", "GL802"} <= ids


def test_static_lock_edges_sees_package_nesting():
    # GL402's static pairs feed the witnessed-vs-static cross-check;
    # the real package has at least one syntactically nested pair
    edges = audit.static_lock_edges()
    assert isinstance(edges, list)


def test_audit_payload_shape(ir_audit):
    st = ExecStore()
    x = jnp.arange(64.0)
    _compile(st, "munge", "payload_site",
             lambda: (lambda a: a + 1.0), x)
    p = audit.audit_payload()
    assert p["enabled"]["ir"] is True
    assert p["events_recorded"] >= 1
    assert set(p["findings"]) == {"ir", "runtime"}
    lg = p["lock_graph"]
    for k in ("witnessed_edges", "static_edges", "witnessed_only",
              "static_only", "cycles", "held_dispatches", "stats"):
        assert k in lg, k


def test_audit_rest_route(ir_audit):
    from h2o_tpu.api.handlers import audit_route
    body = audit_route({})
    assert "lock_graph" in body and "findings" in body
    assert body["enabled"]["ir"] is True


# -- satellite: graftlint module cache keyed on (mtime_ns, size) -------------

def test_module_cache_invalidates_on_size_with_same_mtime(tmp_path):
    """Same-second rewrite with a preserved mtime must still reparse —
    the (st_mtime_ns, st_size) stamp catches what float mtime missed."""
    from h2o_tpu.lint.core import load_module
    p = tmp_path / "m.py"
    p.write_text("x = 1\n")
    st0 = os.stat(p)
    mi1 = load_module(str(p), "m.py")
    assert mi1 is not None and "x = 1" in mi1.source
    p.write_text("x = 1  # grew\n")
    os.utime(p, ns=(st0.st_atime_ns, st0.st_mtime_ns))  # freeze mtime
    mi2 = load_module(str(p), "m.py")
    assert "grew" in mi2.source, "stale AST served after same-mtime rewrite"
