"""Algorithm registry (reference: hex/api/RegisterAlgos.java:17-42 — every
builder registers itself so REST /3/ModelBuilders/{algo} can dispatch)."""

from __future__ import annotations

from typing import Dict, Type


def builders() -> Dict[str, type]:
    from h2o_tpu.models.tree.gbm import GBM
    from h2o_tpu.models.tree.drf import DRF
    reg = {"gbm": GBM, "drf": DRF}
    try:
        from h2o_tpu.models.glm import GLM
        reg["glm"] = GLM
    except ImportError:
        pass
    try:
        from h2o_tpu.models.kmeans import KMeans
        reg["kmeans"] = KMeans
    except ImportError:
        pass
    try:
        from h2o_tpu.models.deeplearning import DeepLearning
        reg["deeplearning"] = DeepLearning
    except ImportError:
        pass
    try:
        from h2o_tpu.models.pca import PCA
        reg["pca"] = PCA
    except ImportError:
        pass
    try:
        from h2o_tpu.models.naive_bayes import NaiveBayes
        reg["naivebayes"] = NaiveBayes
    except ImportError:
        pass
    try:
        from h2o_tpu.models.tree.isofor import (ExtendedIsolationForest,
                                                IsolationForest)
        reg["isolationforest"] = IsolationForest
        reg["extendedisolationforest"] = ExtendedIsolationForest
    except ImportError:
        pass
    try:
        from h2o_tpu.models.svd import SVD
        reg["svd"] = SVD
    except ImportError:
        pass
    try:
        from h2o_tpu.models.glrm import GLRM
        reg["glrm"] = GLRM
    except ImportError:
        pass
    try:
        from h2o_tpu.models.word2vec import Word2Vec
        reg["word2vec"] = Word2Vec
    except ImportError:
        pass
    try:
        from h2o_tpu.models.coxph import CoxPH
        reg["coxph"] = CoxPH
    except ImportError:
        pass
    try:
        from h2o_tpu.models.isotonic import IsotonicRegression
        reg["isotonicregression"] = IsotonicRegression
    except ImportError:
        pass
    try:
        from h2o_tpu.models.aggregator import Aggregator
        reg["aggregator"] = Aggregator
    except ImportError:
        pass
    try:
        from h2o_tpu.models.gam import GAM
        reg["gam"] = GAM
    except ImportError:
        pass
    from h2o_tpu.models.generic import Generic
    reg["generic"] = Generic
    from h2o_tpu.models.ensemble import StackedEnsemble
    reg["stackedensemble"] = StackedEnsemble
    return reg


def builder_class(algo: str) -> type:
    return builders()[algo.lower()]


def model_class(algo: str) -> type:
    return builder_class(algo).model_cls
