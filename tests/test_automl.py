"""StackedEnsemble + Leaderboard + AutoML (reference: hex/ensemble/*,
hex/leaderboard/Leaderboard.java, ai/h2o/automl/AutoML.java)."""

import numpy as np
import pytest

from h2o_tpu.core.frame import Frame, Vec, T_CAT


pytestmark = pytest.mark.slow   # compile-heavy (conftest tier doc)

@pytest.fixture()
def bin_frame(rng):
    n = 2000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    logits = 1.5 * X[:, 0] - X[:, 1] + X[:, 2] * X[:, 3]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-logits))).astype(np.int32)
    return Frame([f"x{j}" for j in range(5)] + ["y"],
                 [Vec(X[:, j]) for j in range(5)] +
                 [Vec(y, T_CAT, domain=["no", "yes"])])


def test_stacked_ensemble_cv_mode(cl, bin_frame):
    from h2o_tpu.models.ensemble import StackedEnsemble
    from h2o_tpu.models.glm import GLM
    from h2o_tpu.models.tree.gbm import GBM
    fr = bin_frame
    fold = Vec((np.arange(fr.nrows) % 3).astype(np.float32))
    fr.add("fold", fold)
    common = dict(fold_column="fold", keep_cross_validation_predictions=True,
                  seed=1)
    gbm = GBM(ntrees=10, max_depth=3, **common).train(
        y="y", training_frame=fr)
    glm = GLM(family="binomial", **common).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[gbm, glm], seed=1).train(
        y="y", training_frame=fr)
    auc_se = se.output["training_metrics"]["AUC"]
    auc_glm = glm.output["training_metrics"]["AUC"]
    assert auc_se >= auc_glm - 0.01     # ensemble >= weakest-ish base
    assert se.output["metalearner_algo"] == "glm"
    raw = np.asarray(se.predict_raw(fr))
    assert raw.shape[1] == 3


def test_stacked_ensemble_blending_mode(cl, bin_frame, rng):
    from h2o_tpu.models.ensemble import StackedEnsemble
    from h2o_tpu.models.tree.drf import DRF
    from h2o_tpu.models.tree.gbm import GBM
    fr = bin_frame
    gbm = GBM(ntrees=8, max_depth=3, seed=2).train(y="y", training_frame=fr)
    drf = DRF(ntrees=8, max_depth=4, seed=2).train(y="y", training_frame=fr)
    se = StackedEnsemble(base_models=[gbm, drf], blending_frame=fr,
                         seed=2).train(y="y", training_frame=fr)
    assert se.output["training_metrics"]["AUC"] > 0.7


def test_leaderboard_ranking(cl, bin_frame):
    from h2o_tpu.models.glm import GLM
    from h2o_tpu.models.leaderboard import Leaderboard
    from h2o_tpu.models.tree.gbm import GBM
    fr = bin_frame
    m1 = GBM(ntrees=10, max_depth=3, seed=1).train(y="y", training_frame=fr)
    m2 = GLM(family="binomial").train(y="y", training_frame=fr)
    lb = Leaderboard("t")
    lb.add(m1, m2)
    rows = lb.rows()
    assert len(rows) == 2
    assert rows[0]["auc"] >= rows[1]["auc"]   # binomial sorts by AUC desc
    assert lb.leader is not None


def test_automl_end_to_end(cl, bin_frame):
    from h2o_tpu.automl import AutoML
    aml = AutoML(max_models=4, seed=42, nfolds=3,
                 include_algos=["glm", "gbm", "stackedensemble"],
                 project_name="t1")
    aml.train(y="y", training_frame=bin_frame)
    assert aml.leader is not None
    rows = aml.leaderboard.rows()
    assert len(rows) >= 2
    # CV metric ordering respected
    aucs = [r["auc"] for r in rows]
    assert aucs == sorted(aucs, reverse=True)
    # events recorded
    stages = {e["stage"] for e in aml.event_log.events}
    assert "init" in stages and "done" in stages
    d = aml.to_dict()
    assert d["leader"] == str(aml.leader.key)


def test_glm_non_negative(cl, bin_frame):
    from h2o_tpu.models.glm import GLM
    m = GLM(family="binomial", non_negative=True).train(
        y="y", training_frame=bin_frame)
    coefs = m.coef()
    non_int = [v for k, v in coefs.items() if k != "Intercept"]
    assert all(v >= -1e-8 for v in non_int)


def test_automl_plan_parity(cl, bin_frame):
    """Round-3 plan depth (AutoML.java:346,457-460): XGBoost steps,
    exploitation phase refining the incumbent, BOTH ensemble variants,
    and WorkAllocations time budgeting."""
    from h2o_tpu.automl import AutoML
    # model-count budget sized so the whole plan completes and the
    # exploitation phase still has room (3 defaults + 3+4 grid + 2 exploit)
    aml = AutoML(max_models=12, seed=7, nfolds=3,
                 include_algos=["xgboost", "gbm", "stackedensemble"],
                 project_name="parity")
    aml.train(y="y", training_frame=bin_frame)
    rows = aml.leaderboard.rows()
    algos = {r["algo"] for r in rows}
    assert "xgboost" in algos          # XGBoost step present
    exploit = [e for e in aml.event_log.events
               if e["stage"].startswith("exploit")]
    assert exploit, "no exploitation step attempted"
    se_names = [r["model_id"] for r in rows
                if "StackedEnsemble" in r["model_id"]]
    assert any("BestOfFamily" in s for s in se_names)
    assert any("AllModels" in s for s in se_names)


def test_automl_respects_max_runtime(cl, bin_frame):
    import time
    from h2o_tpu.automl import AutoML
    t0 = time.time()
    aml = AutoML(max_models=0, max_runtime_secs=25.0, seed=1, nfolds=0,
                 include_algos=["gbm", "glm"], project_name="budgeted")
    aml.train(y="y", training_frame=bin_frame)
    wall = time.time() - t0
    # per-model compiles can overshoot a step boundary, but the loop must
    # stop promptly after the budget — generous 4x bound
    assert wall < 100.0
    assert len(aml.leaderboard.rows()) >= 1


def test_automl_target_encoding_preprocessing(cl, rng):
    """preprocessing=['target_encoding'] (ai/h2o/automl/preprocessing/
    TargetEncoding.java): CV-safe _te columns feed the tree steps."""
    from h2o_tpu.automl.automl import AutoML
    n = 300
    g = rng.integers(0, 4, size=n)
    x = rng.normal(size=n).astype(np.float32)
    y = (x + 0.5 * (g % 2) + rng.normal(size=n) * 0.3 > 0.4)
    fr = Frame(["x", "g", "y"],
               [Vec(x),
                Vec(g.astype(np.int32), T_CAT,
                    domain=["a", "b", "c", "d"]),
                Vec(y.astype(np.int32), T_CAT, domain=["n", "p"])])
    aml = AutoML(max_models=2, nfolds=3, seed=1,
                 include_algos=["GBM"],
                 preprocessing=["target_encoding"])
    aml.train(y="y", training_frame=fr)
    msgs = " ".join(e["message"] for e in aml.event_log.events)
    assert "target encoding applied" in msgs
    lead = aml.leaderboard.leader
    assert lead is not None
    assert "g_te" in lead.output["x"]
    import pytest as _pytest
    with _pytest.raises(ValueError, match="unsupported preprocessing"):
        AutoML(preprocessing=["pca"])


def test_automl_te_models_score_raw_frames(cl, rng):
    """TE-trained models score frames WITHOUT _te columns (the pipeline
    wrapper applies the encoder), incl. a leaderboard frame and new
    data; MOJO export refuses with a clear message."""
    from h2o_tpu.automl.automl import AutoML
    n = 300
    g = rng.integers(0, 3, size=n)
    x = rng.normal(size=n).astype(np.float32)
    y = (x + 0.6 * (g == 1) + rng.normal(size=n) * 0.3 > 0.4)

    def mk(lo, hi):
        return Frame(["x", "g", "y"],
                     [Vec(x[lo:hi]),
                      Vec(g[lo:hi].astype(np.int32), T_CAT,
                          domain=["a", "b", "c"]),
                      Vec(y[lo:hi].astype(np.int32), T_CAT,
                          domain=["n", "p"])])
    tr, lb = mk(0, 200), mk(200, 300)
    aml = AutoML(max_models=1, nfolds=3, seed=1, include_algos=["GBM"],
                 preprocessing=["target_encoding"])
    aml.train(y="y", training_frame=tr, leaderboard_frame=lb)
    lead = aml.leaderboard.leader
    assert lead is not None
    # raw frame (no _te columns) scores through the wrapper
    raw = np.asarray(lead.predict_raw(lb))[: lb.nrows]
    assert raw.shape[1] == 3 and np.isfinite(raw[:, 2]).all()
    # leaderboard ranking on the raw lb frame worked
    assert aml.leaderboard.rows()[0]["auc"] > 0.5
    # mojo export refuses clearly
    from h2o_tpu.mojo import export_genmodel_mojo
    with pytest.raises(NotImplementedError, match="target-encoding"):
        export_genmodel_mojo(lead)
